"""Disaggregated read tier: stateless querier replicas over the shared
object store (store/objstore.py + store/segcache.py) must answer
byte-identically to one standalone server holding every row, a manifest
pointer swap mid-query must yield a consistent snapshot, the
cluster-wide partial-aggregate cache must let one replica reuse another
replica's warm bucket slices, and evicting a segment from the local LRU
while a scan still holds its chunk must defer the unlink until the last
reference drops (docs/CLUSTER.md "Read tier")."""

import gc
import json
import threading
import time
import urllib.request
from types import SimpleNamespace

import numpy as np

from deepflow_tpu.store import objstore
from deepflow_tpu.store.db import Database
from deepflow_tpu.store.objstore import ObjStore, SegmentPublisher
from deepflow_tpu.store.segcache import SegmentCache

TBL = "flow_log.l7_flow_log"
BASE_NS = 1_754_000_000_000_000_000


def _rows(n0: int, n: int) -> list[dict]:
    out = []
    for i in range(n0, n0 + n):
        out.append({
            "time": BASE_NS + i * 1_000_000,
            "flow_id": 100 + i,
            "app_service": ("svc-a", "svc-b", "svc-c")[i % 3],
            "endpoint": f"/api/{'abc'[i % 3]}",
            "request_type": "GET" if i % 2 == 0 else "POST",
            "response_code": (200, 404, 500)[i % 3],
            "response_duration": 10_000 + i * 150,
        })
    return out


def _post(port: int, body: dict) -> dict:
    req = urllib.request.Request(f"http://127.0.0.1:{port}/v1/query",
                                 data=json.dumps(body).encode())
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _canon(x):
    if isinstance(x, bool):
        return x
    if isinstance(x, (int, float)):
        return round(float(x), 6)
    if isinstance(x, list):
        return [_canon(v) for v in x]
    if isinstance(x, dict):
        return {k: _canon(v) for k, v in x.items()}
    return x


# -- satellite: LRU eviction vs in-flight scan ------------------------------

def _published(tmp_path, batches):
    """Seal one segment per batch locally, publish them, return
    (store, [segment manifest entries])."""
    db = Database(data_dir=str(tmp_path / "ing"), shard_id=1,
                  storage=True)
    t = db.table(TBL)
    for rows in batches:
        t.append_rows(rows)
        assert db.flush_to_tier() == len(rows)
    SegmentPublisher(ObjStore(str(tmp_path / "obj")), 1) \
        .publish(db.tier_store)
    store = ObjStore(str(tmp_path / "obj"))
    doc = store.get_pointer(objstore.pointer_name(1))
    segs = doc["tables"][TBL]["segments"]
    assert len(segs) == len(batches)
    return store, segs


class _Holder:
    pass


def test_eviction_defers_unlink_until_last_ref_drops(tmp_path):
    """A segment evicted from the byte-budgeted LRU while a (slow) scan
    still pins its mmap must keep its file until the scan's reference
    drops — then, and only then, the deferred unlink fires."""
    store, segs = _published(tmp_path, [_rows(0, 8), _rows(8, 8)])
    cache = SegmentCache(str(tmp_path / "cache"), store, max_bytes=1)
    rs = [SimpleNamespace(key=(1, TBL, s["fn"]), shard=1, table=TBL,
                          fn=s["fn"]) for s in segs]

    h1 = _Holder()
    ent1 = cache.pin(rs[0], h1)
    seg1, path1 = ent1["seg"], ent1["path"]
    import os
    assert os.path.exists(path1)

    got, errs = [], []

    def _slow_scan():
        try:
            for _ in range(10):
                got.append(np.asarray(seg1.column("flow_id")).copy())
                time.sleep(0.01)
        except Exception as e:  # pragma: no cover - the regression
            errs.append(e)

    scan = threading.Thread(target=_slow_scan)
    scan.start()
    # budget of 1 byte: the second pin must evict the first segment
    # while the scan above still holds it
    h2 = _Holder()
    cache.pin(rs[1], h2)
    snap = cache.snapshot()
    assert snap["evictions"] == 1
    assert snap["deferred_unlinks"] == 1
    assert snap["rows_evicted"] == 8
    assert ent1["condemned"] and not ent1["unlinked"]
    assert os.path.exists(path1), "unlink ran while a scan held the mmap"
    scan.join(timeout=10)
    assert not errs
    want = np.arange(100, 108)
    for arr in got:
        np.testing.assert_array_equal(arr, want)
    # drop the last reference: the finalizer fires the deferred unlink
    del h1, ent1, seg1
    gc.collect()
    deadline = time.monotonic() + 5
    while os.path.exists(path1) and time.monotonic() < deadline:
        gc.collect()
        time.sleep(0.02)
    assert not os.path.exists(path1)


def test_release_never_blocks_on_held_cache_lock(tmp_path):
    """_release runs from weakref.finalize callbacks, which GC can fire
    at any allocation point — including in a thread that currently
    holds the cache lock inside pin()/discard(). It must never block on
    the (non-reentrant) lock: the release is deferred and drained by
    the next cache operation."""
    import os
    store, segs = _published(tmp_path, [_rows(0, 8)])
    cache = SegmentCache(str(tmp_path / "cache"), store)
    rs = SimpleNamespace(key=(1, TBL, segs[0]["fn"]), shard=1,
                         table=TBL, fn=segs[0]["fn"])
    h = _Holder()
    ent = cache.pin(rs, h)
    cache.discard(rs.key)          # condemned while still pinned
    assert ent["condemned"] and not ent["unlinked"]
    assert cache._lock.acquire()   # the GC-interrupted thread's state
    try:
        done = []
        t = threading.Thread(target=lambda: (cache._release(ent),
                                             done.append(True)))
        t.start()
        t.join(timeout=5)
        assert done, "finalizer release blocked on the held cache lock"
    finally:
        cache._lock.release()
    # the deferred release unlinks on the next cache operation
    cache.snapshot()
    assert ent["unlinked"] and not os.path.exists(ent["path"])


def test_publisher_noop_when_tier_unchanged(tmp_path):
    db = Database(data_dir=str(tmp_path / "ing"), shard_id=1,
                  storage=True)
    db.table(TBL).append_rows(_rows(0, 8))
    db.flush_to_tier()
    pub = SegmentPublisher(ObjStore(str(tmp_path / "obj")), 1)
    assert pub.maybe_publish(db.tier_store) is not None
    assert pub.publish_gen == 1
    # unchanged tier: no pointer swap, no gen churn for pollers
    assert pub.maybe_publish(db.tier_store) is None
    assert pub.publish_gen == 1
    db.table(TBL).append_rows(_rows(8, 8))
    db.flush_to_tier()
    assert pub.maybe_publish(db.tier_store) is not None
    assert pub.publish_gen == 2


# -- the byte-identity contract ---------------------------------------------

def _cluster(tmp_path, n_queriers=2):
    from deepflow_tpu.server import Server
    obj = str(tmp_path / "obj")
    ingest = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    sync_port=0, shard_id=1, cluster_advertise="",
                    storage=True, data_dir=str(tmp_path / "ingest"),
                    objstore=obj, publish_interval_s=60.0).start()
    seed_addr = f"127.0.0.1:{ingest.query_port}"
    qs = [Server(host="127.0.0.1", ingest_port=0, query_port=0,
                 sync_port=0, shard_id=8 + i, role="querier",
                 objstore=obj, cluster_seed=seed_addr,
                 readtier_poll_s=60.0).start()
          for i in range(n_queriers)]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all(len(q.api.federation.remote_peers()) == 1 for q in qs):
            break
        time.sleep(0.05)
    assert all(len(q.api.federation.remote_peers()) == 1 for q in qs), \
        "queriers never joined the seed"
    return ingest, qs


def test_readtier_answers_byte_identical(tmp_path):
    """(a) one standalone node vs (b) ingest shard + 2 cold querier
    replicas vs (c) a warm distributed-partial hit: all byte-identical,
    with sealed history answered by the replicas and live (unflushed)
    rows by the ingest shard exactly once."""
    from deepflow_tpu.server import Server
    solo = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                  sync_port=0).start()
    ingest, qs = _cluster(tmp_path)
    try:
        solo.db.table(TBL).append_rows(_rows(0, 24))
        # 16 rows sealed + published; 8 stay in the live stripes
        ingest.db.table(TBL).append_rows(_rows(0, 16))
        assert ingest.db.flush_to_tier() == 16
        assert ingest.publisher.maybe_publish(ingest.db.tier_store)
        ingest.db.table(TBL).append_rows(_rows(16, 8))
        for q in qs:
            q.readtier.poll()
            t = q.db.table(TBL)
            assert len(t) == 16 and t.tier is not None \
                and t.tier.rows == 16

        sqls = [
            "SELECT app_service, Count(*) AS n, "
            "Sum(response_duration) AS s, Min(response_code) AS mn, "
            "Max(response_code) AS mx FROM l7_flow_log "
            "GROUP BY app_service ORDER BY app_service",
            "SELECT Count(DISTINCT endpoint) AS d, Count(*) AS n "
            "FROM l7_flow_log",
            "SELECT app_service, request_type, Count(*) AS n "
            "FROM l7_flow_log GROUP BY app_service, request_type "
            "ORDER BY app_service, request_type",
            "SELECT time, app_service, endpoint FROM l7_flow_log "
            "WHERE response_code = 200 ORDER BY time DESC LIMIT 7",
        ]
        for sql in sqls:
            body = {"sql": sql, "db": "flow_log"}
            want = _post(solo.query_port, body)["result"]
            for q in qs:
                got = _post(q.query_port, body)
                assert got["federation"]["missing_shards"] == [], sql
                assert _canon(got["result"]) == _canon(want), sql
        # handshake audit: the replicas adopted the publish gen, so the
        # ingest shard must have answered with its sealed rows excluded
        # (a total of 24 == 16 sealed + 8 live proves exactly-once)
        for q in qs:
            assert q.readtier.snapshot()["adopted"] == {"1": 1}

        # (c) warm distributed partial: a bucketable aggregate warm on
        # q0 ONLY, advertised through the join gossip, must be fetched
        # (not rescanned) by q1 — and still answer byte-identically
        bq = ("SELECT endpoint, Count(*) AS n, "
              "Max(response_duration) AS m FROM l7_flow_log "
              "GROUP BY endpoint ORDER BY endpoint")
        body = {"sql": bq, "db": "flow_log"}
        want = _post(solo.query_port, body)["result"]
        assert _canon(_post(qs[0].query_port, body)["result"]) \
            == _canon(want)
        assert qs[0].partial_cache.advertised_digests()
        qs[0].membership._join_once()   # push adverts to the seed
        qs[1].membership._join_once()   # pull the merged advert map
        got = _post(qs[1].query_port, body)
        assert _canon(got["result"]) == _canon(want)
        assert qs[1].api.query_cache.counters["dist_hits"] >= 1
        q1_fetch = qs[1].partial_cache.snapshot()
        q0_serve = qs[0].partial_cache.snapshot()
        assert q1_fetch["fetched_buckets"] >= 1
        assert q1_fetch["remap_failures"] == 0
        # the compute-once ledger: warm buckets served == buckets
        # fetched, nothing rescanned on the cold replica
        assert q0_serve["served_buckets"] == q1_fetch["fetched_buckets"]

        # queriers must never enter the ingest hash ring / peer scatter
        ingest_sids = {p.shard_id
                       for p in ingest.membership.peers(role="ingest")}
        assert ingest_sids == {1}
        assert ingest.api.federation.remote_peers() == []

        # /v1/health surfaces the read-tier + cache ledgers
        with urllib.request.urlopen(
                f"http://127.0.0.1:{qs[0].query_port}/v1/health",
                timeout=10) as resp:
            health = json.loads(resp.read())
        assert health["readtier"]["tables"][TBL]["rows"] == 16
        assert "partial_cache" in health
    finally:
        for q in qs:
            q.stop()
        ingest.stop()
        solo.stop()


def test_manifest_swap_mid_query_consistent_snapshot(tmp_path):
    """A pointer swap while a query is in flight must wait for the
    frozen snapshot, and every answer before/during/after the swap must
    equal the standalone answer — never a torn or double-counted one."""
    from deepflow_tpu.server import Server
    solo = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                  sync_port=0).start()
    ingest, qs = _cluster(tmp_path, n_queriers=1)
    q = qs[0]
    body = {"sql": "SELECT app_service, Count(*) AS n, "
                   "Sum(response_duration) AS s FROM l7_flow_log "
                   "GROUP BY app_service ORDER BY app_service",
            "db": "flow_log"}
    try:
        solo.db.table(TBL).append_rows(_rows(0, 16))
        ingest.db.table(TBL).append_rows(_rows(0, 16))
        assert ingest.db.flush_to_tier() == 16
        assert ingest.publisher.maybe_publish(ingest.db.tier_store)
        q.readtier.poll()
        want16 = _post(solo.query_port, body)["result"]
        assert _canon(_post(q.query_port, body)["result"]) \
            == _canon(want16)

        # gen 2 lands while the querier holds a frozen snapshot
        solo.db.table(TBL).append_rows(_rows(16, 8))
        want24 = _post(solo.query_port, body)["result"]
        ingest.db.table(TBL).append_rows(_rows(16, 8))
        assert ingest.db.flush_to_tier() == 8
        assert ingest.publisher.maybe_publish(ingest.db.tier_store)
        with q.readtier.freeze():
            polled = threading.Thread(target=q.readtier.poll)
            polled.start()
            polled.join(timeout=0.3)
            assert polled.is_alive(), \
                "pointer adoption ran inside a frozen snapshot"
            # frozen at gen 1 while the shard is at gen 2: the shard
            # answers in full, the stale local view is excluded — the
            # answer is still exact, never torn. (Direct api call: an
            # HTTP round-trip would block on the freeze we hold; the
            # coordinator re-enters it on this thread.)
            got = q.api.query(body)
            assert _canon(got["result"]) == _canon(want24)
            assert q.readtier.snapshot()["adopted"] == {"1": 1}
        polled.join(timeout=10)
        assert not polled.is_alive()
        assert q.readtier.snapshot()["adopted"] == {"1": 2}
        assert len(q.db.table(TBL)) == 24
        # after adoption the handshake re-engages: replica serves all
        # 24 sealed rows, the shard answers only its (empty) live set
        assert _canon(_post(q.query_port, body)["result"]) \
            == _canon(want24)
    finally:
        q.stop()
        ingest.stop()
        solo.stop()


def test_handshake_refuses_stale_exclusion_after_compaction(tmp_path):
    """Between a compaction commit and the next publish tick the
    shard's publisher.current still names the retired fns: the adopted
    gen matches but the exclusion set matches nothing while the
    replacement run holds the same rows. The shard must NOT ack in that
    window (it answers in full, the coordinator drops its adopted
    segments) or every compacted row is counted twice."""
    from deepflow_tpu.server import Server
    solo = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                  sync_port=0).start()
    ingest, qs = _cluster(tmp_path, n_queriers=1)
    q = qs[0]
    body = {"sql": "SELECT app_service, Count(*) AS n, "
                   "Sum(response_duration) AS s FROM l7_flow_log "
                   "GROUP BY app_service ORDER BY app_service",
            "db": "flow_log"}
    try:
        solo.db.table(TBL).append_rows(_rows(0, 16))
        want = _post(solo.query_port, body)["result"]
        # two small sealed segments, published at gen 1 and adopted
        for lo in (0, 8):
            ingest.db.table(TBL).append_rows(_rows(lo, 8))
            assert ingest.db.flush_to_tier() == 8
        assert ingest.publisher.maybe_publish(ingest.db.tier_store)
        q.readtier.poll()
        assert q.readtier.snapshot()["adopted"] == {"1": 1}
        assert _canon(_post(q.query_port, body)["result"]) \
            == _canon(want)

        # compaction replaces both published fns with one sorted run;
        # publish_interval_s=60 keeps publisher.current stale at gen 1
        import os
        res = ingest.db.compact_tier(min_merge=2)
        assert res["segments_replaced"] == 2
        gen, fn_sets = ingest.publisher.current
        assert gen == 1 and fn_sets[TBL]
        live = {os.path.basename(s.path)
                for s in ingest.db.table(TBL).tier.segments()}
        assert not (fn_sets[TBL] & live), "compaction kept published fns"

        # the querier still holds gen 1; the shard must answer in full
        # (no ack) and the answer must stay exact — not doubled
        got = _post(q.query_port, body)
        assert got["federation"]["missing_shards"] == []
        assert _canon(got["result"]) == _canon(want)

        # the next publish tick re-arms the handshake at gen 2
        assert ingest.publisher.maybe_publish(ingest.db.tier_store)
        q.readtier.poll()
        assert q.readtier.snapshot()["adopted"] == {"1": 2}
        assert _canon(_post(q.query_port, body)["result"]) \
            == _canon(want)
    finally:
        q.stop()
        ingest.stop()
        solo.stop()


def test_querier_cache_rooted_in_subdir_preserves_data_dir(tmp_path):
    """The segment cache wipes its root at startup, so a querier must
    root it in <data_dir>/segcache — pointing --data-dir at an existing
    directory (e.g. an ingest node's tier) must not destroy it."""
    import os
    from deepflow_tpu.server import Server
    data = tmp_path / "data"
    (data / "tier").mkdir(parents=True)
    keep = data / "tier" / "seg-000001.bin"
    keep.write_bytes(b"precious segment bytes")
    manifest = data / "MANIFEST.json"
    manifest.write_text("{}")
    q = Server(host="127.0.0.1", ingest_port=0, query_port=0,
               sync_port=0, shard_id=9, role="querier",
               objstore=str(tmp_path / "obj"),
               data_dir=str(data)).start()
    try:
        assert q.segcache.root == os.path.join(str(data), "segcache")
        assert keep.read_bytes() == b"precious segment bytes"
        assert manifest.exists()
    finally:
        q.stop()
