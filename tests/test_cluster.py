"""Cluster federation tests: wire format, sketch, membership, and the
merge-equivalence contract — a golden corpus split over 3 shards must
answer DF-SQL / PromQL / Tempo / flame queries identically to one
standalone server holding every row (docs/CLUSTER.md)."""

import json
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from deepflow_tpu.cluster import wire
from deepflow_tpu.cluster.membership import Peer, PeerDirectory
from deepflow_tpu.cluster.sketch import HistogramSketch
from deepflow_tpu.store.schema import (L7_PROTOS, PROFILE_EVENT_TYPES,
                                       RESPONSE_STATUS, TPU_SPAN_KINDS)


# -- wire format -----------------------------------------------------------

def test_wire_roundtrip_table():
    obj = {"columns": ["n", "name", "ratio"],
           "values": [[1, "alpha", 2.5], [2, "beta", -0.25],
                      [3, "", 1e12]],
           "extra": {"groups": 3}}
    obj2, sid = wire.decode_result(wire.encode_result(obj, shard_id=7))
    assert sid == 7
    assert obj2["columns"] == obj["columns"]
    assert obj2["values"] == obj["values"]
    assert obj2["extra"] == {"groups": 3}
    # int column survives as int (i64 path), float column as float
    assert isinstance(obj2["values"][0][0], int)
    assert isinstance(obj2["values"][0][2], float)


def test_wire_roundtrip_empty_and_large():
    obj = {"columns": ["a"], "values": []}
    obj2, _ = wire.decode_result(wire.encode_result(obj))
    assert obj2["values"] == []
    # > 512B payload exercises the frame layer's zlib path
    big = {"columns": ["s", "v"],
           "values": [[f"stack;frame_{i};leaf", i] for i in range(200)]}
    big2, sid = wire.decode_result(wire.encode_result(big, shard_id=3))
    assert sid == 3 and big2["values"] == big["values"]


def test_wire_json_fallback():
    obj = {"spans": [{"span_id": "s1", "start_ns": 5}], "unknown": True}
    obj2, sid = wire.decode_result(wire.encode_result(obj, shard_id=2))
    assert obj2 == obj and sid == 2
    with pytest.raises(wire.WireError):
        wire.decode_result(b"\x00\x01")


# -- histogram sketch ------------------------------------------------------

def test_sketch_merge_matches_single_sketch():
    values = np.geomspace(1.0, 1e6, 500)
    whole = HistogramSketch()
    whole.add_many(values)
    merged = HistogramSketch()
    for part in np.array_split(values, 3):
        s = HistogramSketch()
        s.add_many(part)
        merged.merge(HistogramSketch.from_dict(s.to_dict()))  # wire form
    assert merged.count == whole.count == 500
    for p in (50, 90, 95, 99):
        assert merged.percentile(p) == whole.percentile(p)
        # ~2% relative error vs the exact percentile (gamma = 1.02)
        exact = float(np.percentile(values, p))
        assert merged.percentile(p) == pytest.approx(exact, rel=0.05)


def test_sketch_zeros_and_bounds():
    s = HistogramSketch()
    s.add_many(np.array([0.0, 0.0, 10.0, 20.0]))
    assert s.percentile(25) == 0.0            # zeros rank first
    assert s.percentile(100) <= 20.0          # clamped to observed max
    assert HistogramSketch().percentile(99) == 0.0


# -- membership directory --------------------------------------------------

def test_peer_directory_version_semantics():
    d = PeerDirectory()
    assert d.upsert(Peer(shard_id=1, addr="a:1", epoch=10)) is True
    assert d.version == 1
    # heartbeat (same addr + epoch) refreshes last_seen, no version bump
    assert d.upsert(Peer(shard_id=1, addr="a:1", epoch=10)) is False
    assert d.version == 1
    # restart (epoch bump) and address move ARE membership changes
    assert d.upsert(Peer(shard_id=1, addr="a:1", epoch=11)) is True
    assert d.upsert(Peer(shard_id=1, addr="b:2", epoch=11)) is True
    assert d.version == 3
    d.upsert(Peer(shard_id=2, addr="c:3", epoch=5))
    assert len(d.alive()) == 2
    # adopt: a joiner takes the seed's snapshot wholesale
    j = PeerDirectory()
    j.adopt(d.snapshot())
    assert j.version == d.version
    assert [p["shard_id"] for p in j.snapshot()["peers"]] == [1, 2]
    # stale snapshot (lower version) is ignored
    j.upsert(Peer(shard_id=3, addr="d:4", epoch=1))
    v = j.version
    j.adopt({"version": 1, "peers": []})
    assert j.version == v and len(j.snapshot()["peers"]) == 3


# -- satellite: unchanged analyzer list must not rebalance the sender ------

def test_apply_analyzers_unchanged_list_is_noop():
    """Re-applying the SAME analyzer assignment (every sync response
    carries it) must not tear down / reconnect the sender."""
    from types import SimpleNamespace

    from deepflow_tpu.agent.sender import UniformSender
    from deepflow_tpu.agent.synchronizer import Synchronizer

    sender = UniformSender(servers=[("127.0.0.1", 20033)])
    fake = SimpleNamespace(agent=SimpleNamespace(sender=sender),
                           _configured_servers=[("127.0.0.1", 20033)])
    Synchronizer._apply_analyzers(fake, ["10.0.0.1:30033",
                                         "10.0.0.2:30033"])
    assert sender.stats.get("rebalances") == 1
    servers_obj = sender.servers
    assert servers_obj == [("10.0.0.1", 30033), ("10.0.0.2", 30033)]
    # the same list again: no reassignment, no rebalance, no reconnect
    for _ in range(3):
        Synchronizer._apply_analyzers(fake, ["10.0.0.1:30033",
                                             "10.0.0.2:30033"])
    assert sender.servers is servers_obj
    assert sender.stats.get("rebalances") == 1
    assert sender.stats["reconnects"] == 0
    # empty assignment falls back to the configured servers (a change)
    Synchronizer._apply_analyzers(fake, [])
    assert sender.servers == [("127.0.0.1", 20033)]
    assert sender.stats.get("rebalances") == 2


# -- golden corpus ---------------------------------------------------------

BASE_S = 1_754_000_000
BASE_NS = BASE_S * 1_000_000_000

_L7 = {n: i for i, n in enumerate(L7_PROTOS)}
_RS = {n: i for i, n in enumerate(RESPONSE_STATUS)}
_EV = {n: i for i, n in enumerate(PROFILE_EVENT_TYPES)}
_KIND = {n: i for i, n in enumerate(TPU_SPAN_KINDS)}


def _corpus() -> dict:
    """Rows per table. Every start_ns is unique (deterministic trace
    trees) and every flame stack total is distinct (deterministic child
    order)."""
    l7 = []
    svcs = ("svc-a", "svc-b", "svc-c")
    protos = (_L7["http1"], _L7["dns"], _L7["http1"], _L7["mysql"])
    # trace-1: 5 spans (s1 root), trace-2: 3 spans, rest single-span
    span_plan = {0: ("trace-1", "s1", ""), 1: ("trace-1", "s2", "s1"),
                 2: ("trace-1", "s3", "s1"), 3: ("trace-1", "s4", "s2"),
                 4: ("trace-1", "s5", "s2"),
                 5: ("trace-2", "r1", ""), 6: ("trace-2", "r2", "r1"),
                 7: ("trace-2", "r3", "r2")}
    for i in range(24):
        tid, sid, parent = span_plan.get(
            i, (f"solo-{i}", f"sp-{i}", ""))
        l7.append({
            "time": BASE_NS + i * 1_000_000,      # unique start_ns
            "flow_id": 100 + i,
            "app_service": svcs[i % 3],
            "ip_src": f"10.0.0.{i % 4}", "ip_dst": "10.0.1.1",
            "port_src": 40000 + i, "port_dst": 8080,
            "l7_protocol": protos[i % 4],
            "request_type": "GET" if i % 2 == 0 else "POST",
            "endpoint": f"/api/{'abc'[i % 3]}",
            "request_id": i,
            "response_status": (_RS["ok"] if i % 5 else
                                _RS["server_error"]),
            "response_code": (200, 404, 500)[i % 3],
            "response_duration": 10_000 + i * 150,  # small adjacent gaps
            "trace_id": tid, "span_id": sid, "parent_span_id": parent,
        })
    prom = []
    for i in range(6):
        prom.append({"time": BASE_S + i * 10,
                     "metric_name": "fed_requests_total",
                     "labels_json": '{"job": "a"}',
                     "value": float(100 + i * 10)})
        prom.append({"time": BASE_S + i * 10,
                     "metric_name": "fed_requests_total",
                     "labels_json": '{"job": "b"}',
                     "value": float(50 + i * 5)})
        prom.append({"time": BASE_S + i * 10,
                     "metric_name": "fed_gauge",
                     "labels_json": '{"host": "h1"}',
                     "value": float(7 + i)})
    profile = []
    for stack, per, n in (("main;ingest;decode", 10, 4),
                          ("main;ingest;write", 5, 5),
                          ("main;query;merge", 6, 2)):
        for k in range(n):
            profile.append({"time": BASE_NS + len(profile) * 1000,
                            "app_service": "svc-prof",
                            "process_name": "df", "event_type": _EV["on-cpu"],
                            "profiler": "py-spy", "stack": stack,
                            "value": per, "count": 1})
    tpu = []
    plan = (("mod_a", "convolution", "conv.1", 900),
            ("mod_a", "all-reduce", "ar.1", 410),
            ("mod_b", "convolution", "conv.2", 170),
            ("mod_b", "other", "copy.3", 65))
    for j, (mod, cat, op, dur) in enumerate(plan):
        for k in range(3):
            tpu.append({"time": BASE_NS + (j * 3 + k) * 500,
                        "duration_ns": dur + k, "device_id": k,
                        "kind": _KIND["device-compute"],
                        "hlo_module": mod, "hlo_category": cat,
                        "hlo_op": op, "flops": 1000})
    # one host-side span: must be excluded by the default TpuFlame view
    tpu.append({"time": BASE_NS, "duration_ns": 9999, "device_id": 0,
                "kind": _KIND["host-compile"], "hlo_module": "mod_h",
                "hlo_category": "compile", "hlo_op": "jit", "flops": 0})
    return {"flow_log.l7_flow_log": l7, "prometheus.samples": prom,
            "profile.in_process_profile": profile,
            "profile.tpu_hlo_span": tpu}


def _canon(x):
    if isinstance(x, bool):
        return x
    if isinstance(x, (int, float)):
        return round(float(x), 6)
    if isinstance(x, list):
        return [_canon(v) for v in x]
    if isinstance(x, dict):
        return {k: _canon(v) for k, v in x.items()}
    return x


def _get(port: int, path: str, params: dict | None = None) -> dict:
    url = f"http://127.0.0.1:{port}{path}"
    if params:
        url += "?" + urllib.parse.urlencode(params)
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def _post(port: int, path: str, body: dict) -> dict:
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=json.dumps(body).encode())
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _sorted_prom(data: dict) -> dict:
    data = dict(data)
    data["result"] = sorted(
        data.get("result", []),
        key=lambda s: json.dumps(s.get("metric", {}), sort_keys=True))
    return data


# -- the 3-shard equivalence + degraded-mode integration test --------------

def test_cluster_federation_end_to_end():
    from deepflow_tpu.server import Server

    corpus = _corpus()
    solo = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                  sync_port=0).start()
    seed = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                  sync_port=0, shard_id=1, cluster_advertise="").start()
    shards = [seed]
    try:
        seed_addr = f"127.0.0.1:{seed.query_port}"
        for sid in (2, 3):
            shards.append(Server(
                host="127.0.0.1", ingest_port=0, query_port=0,
                sync_port=0, shard_id=sid,
                cluster_seed=seed_addr).start())

        # corpus: all rows on solo, round-robin across the 3 shards
        for name, rows in corpus.items():
            solo.db.table(name).append_rows(rows)
            for i, row in enumerate(rows):
                shards[i % 3].db.table(name).append_rows([row])

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(seed.api.federation.remote_peers()) == 2:
                break
            time.sleep(0.05)
        assert len(seed.api.federation.remote_peers()) == 2, \
            "joiners never registered with the seed"
        sp, fp = solo.query_port, seed.query_port

        # -- satellite: ingested rows carry the receiving shard's id ----
        l7 = seed.db.table("flow_log.l7_flow_log")
        codes = set()
        for ch in l7.snapshot():
            if ch:
                codes.update(np.unique(ch["shard_id"]).tolist())
        assert codes == {1}

        # -- DF-SQL: exact multi-agg push-down --------------------------
        exact_sql = [
            "SELECT app_service, Sum(response_duration) AS s, "
            "Count(*) AS n, Avg(response_duration) AS a, "
            "Min(response_code) AS mn, Max(response_code) AS mx "
            "FROM l7_flow_log GROUP BY app_service ORDER BY app_service",
            "SELECT Count(DISTINCT endpoint) AS d, Count(*) AS n "
            "FROM l7_flow_log",
            "SELECT app_service, Count(*) AS n FROM l7_flow_log "
            "GROUP BY app_service HAVING Count(*) > 5 "
            "ORDER BY app_service",
            "SELECT time, app_service, endpoint FROM l7_flow_log "
            "WHERE response_code = 200 ORDER BY time DESC LIMIT 7",
            "SELECT app_service, Last(response_code) AS lc "
            "FROM l7_flow_log GROUP BY app_service ORDER BY app_service",
            # dict + enum group keys: shard-local codes must never merge
            "SELECT l7_protocol, response_status, Count(*) AS n "
            "FROM l7_flow_log GROUP BY l7_protocol, response_status "
            "ORDER BY l7_protocol, response_status",
        ]
        for sql in exact_sql:
            body = {"sql": sql, "db": "flow_log"}
            want = _post(sp, "/v1/query", body)["result"]
            got = _post(fp, "/v1/query", body)
            assert got["federation"]["missing_shards"] == [], sql
            assert got["federation"]["shards"] == 3, sql
            assert _canon(got["result"]) == _canon(want), sql

        # percentile is the one documented-approximate merge (~2%)
        for p in (50, 95):
            sql = (f"SELECT Percentile(response_duration, {p}) AS p "
                   "FROM l7_flow_log")
            body = {"sql": sql, "db": "flow_log"}
            want = _post(sp, "/v1/query", body)["result"]["values"][0][0]
            got = _post(fp, "/v1/query", body)["result"]["values"][0][0]
            assert got == pytest.approx(want, rel=0.03), (p, got, want)

        # federated total == union of the per-shard counts
        n_union = sum(len(s.db.table("flow_log.l7_flow_log"))
                      for s in shards)
        body = {"sql": "SELECT Count(*) AS n FROM l7_flow_log",
                "db": "flow_log"}
        assert _post(fp, "/v1/query", body)["result"]["values"][0][0] \
            == n_union == 24
        # GROUP BY shard_id audits the split (exactly one group/shard)
        body = {"sql": "SELECT shard_id, Count(*) AS n FROM l7_flow_log "
                       "GROUP BY shard_id ORDER BY shard_id",
                "db": "flow_log"}
        audit = _post(fp, "/v1/query", body)["result"]["values"]
        assert [r[0] for r in audit] == [1, 2, 3]
        assert sum(r[1] for r in audit) == n_union

        # -- PromQL: raw-selector federation is exact -------------------
        prom_queries = (
            "sum(rate(fed_requests_total[50s]))",
            "sum by (job) (rate(fed_requests_total[50s]))",
            "fed_requests_total",
            "max(fed_gauge)",
        )
        rng = {"start": BASE_S + 50, "end": BASE_S + 50, "step": 15}
        for q in prom_queries:
            want = _get(sp, "/prom/api/v1/query_range",
                        {"query": q, **rng})
            got = _get(fp, "/prom/api/v1/query_range",
                       {"query": q, **rng})
            assert want["status"] == got["status"] == "success", q
            assert "federation" not in got, q
            assert _canon(_sorted_prom(got["data"])) \
                == _canon(_sorted_prom(want["data"])), q
        inst = {"query": "fed_gauge", "time": BASE_S + 55}
        want = _get(sp, "/prom/api/v1/query", inst)
        got = _get(fp, "/prom/api/v1/query", inst)
        assert _canon(_sorted_prom(got["data"])) \
            == _canon(_sorted_prom(want["data"]))

        # -- Tempo: search + cross-shard trace assembly -----------------
        window = {"start": BASE_S - 10, "end": BASE_S + 3600,
                  "limit": 50}
        for extra in ({}, {"tags": 'service.name="svc-a"'},
                      {"minDuration": "2ms"}):
            want = _get(sp, "/api/search", {**window, **extra})
            got = _get(fp, "/api/search", {**window, **extra})
            assert got.pop("federation")["missing_shards"] == []
            assert _canon(got) == _canon(want), extra
        for tid in ("trace-1", "trace-2"):
            want = _get(sp, f"/api/traces/{tid}")
            got = _get(fp, f"/api/traces/{tid}")
            assert _canon(got) == _canon(want), tid
            want = _post(sp, "/v1/trace/Tracing", {"trace_id": tid})
            got = _post(fp, "/v1/trace/Tracing", {"trace_id": tid})
            fed = got["result"].pop("federation")
            assert fed["missing_shards"] == []
            assert _canon(got) == _canon(want), tid
        # trace-1's spans really are split across shards
        per_shard = [len(s.api.collect_trace_spans("trace-1"))
                     for s in shards]
        assert sorted(per_shard) == [1, 2, 2] and sum(per_shard) == 5

        # -- flame graphs -----------------------------------------------
        body = {"app_service": "svc-prof"}
        want = _post(sp, "/v1/profile/ProfileTracing", body)
        got = _post(fp, "/v1/profile/ProfileTracing", body)
        assert got["federation"]["missing_shards"] == []
        assert _canon(got["result"]) == _canon(want["result"])
        assert got["result"]["total_value"] == 10 * 4 + 5 * 5 + 6 * 2
        want = _post(sp, "/v1/profile/TpuFlame", {})
        got = _post(fp, "/v1/profile/TpuFlame", {})
        assert got["federation"]["missing_shards"] == []
        assert _canon(got["result"]) == _canon(want["result"])
        assert "mod_h" not in json.dumps(got["result"])  # host excluded

        # -- membership surfaces ----------------------------------------
        peers = _get(fp, "/v1/cluster/peers")
        assert [p["shard_id"] for p in peers["peers"]] == [1, 2, 3]
        assert peers["version"] >= 3
        # a joiner adopts the seed's full directory (gossip readback
        # rides the 2s join heartbeat — poll one round)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            j2 = _get(shards[1].query_port, "/v1/cluster/peers")
            if len(j2["peers"]) == 3:
                break
            time.sleep(0.2)
        assert [p["shard_id"] for p in j2["peers"]] == [1, 2, 3]
        status = _get(fp, "/v1/cluster/status")
        assert status["shard_id"] == 1
        by_id = {p["shard_id"]: p for p in status["peers"]}
        assert all(by_id[s]["alive"] for s in (1, 2, 3))
        # "raw_rows": physical per-shard counts (replicated rows counted
        # once per replica), renamed so the column says what it is
        assert by_id[2]["raw_rows"] and \
            by_id[2]["latency_ms"] is not None
        assert "rows" not in by_id[2]
        health = _get(fp, "/v1/health")
        assert health["cluster"]["peers_alive"] == 3

        # -- degraded mode: a killed shard yields an annotated partial --
        shards[2].stop()
        body = {"sql": "SELECT app_service, Count(*) AS n "
                       "FROM l7_flow_log GROUP BY app_service "
                       "ORDER BY app_service", "db": "flow_log"}
        got = _post(fp, "/v1/query", body)   # HTTP 200, not a 500
        assert got["federation"]["missing_shards"] == [3]
        n_partial = sum(r[1] for r in got["result"]["values"])
        assert n_partial == len(shards[0].db.table(
            "flow_log.l7_flow_log")) + len(shards[1].db.table(
                "flow_log.l7_flow_log"))
        # every fed_gauge row lives on the dead shard: the metric is now
        # unknown on every REACHABLE shard, which must degrade to an
        # annotated empty partial, not an unknown-metric error
        got = _get(fp, "/prom/api/v1/query_range",
                   {"query": "sum(fed_gauge)", **rng})
        assert got["status"] == "success"
        assert got["data"]["result"] == []
        assert got["federation"]["missing_shards"] == [3]
        assert any("shards [3]" in w for w in got.get("warnings", []))
        # a metric the survivors do hold still answers with partial data
        got = _get(fp, "/prom/api/v1/query_range",
                   {"query": "sum(rate(fed_requests_total[50s]))", **rng})
        assert got["status"] == "success" and got["data"]["result"]
        assert got["federation"]["missing_shards"] == [3]
        got = _get(fp, "/api/search", window)
        assert got["federation"]["missing_shards"] == [3]
        got = _get(fp, f"/api/traces/trace-1")
        assert got["batches"][0]["spans"]          # partial, still a 200
        status = _get(fp, "/v1/cluster/status")
        by_id = {p["shard_id"]: p for p in status["peers"]}
        assert by_id[3]["alive"] is False and by_id[2]["alive"] is True

        # -- ledger balance over every fan-out hop ----------------------
        snap = seed.telemetry.snapshot()
        cluster_hops = [h for h in snap["pipeline"]
                        if h["hop"].startswith("cluster.")]
        assert {h["hop"] for h in cluster_hops} >= {
            "cluster.sql", "cluster.promql", "cluster.tempo",
            "cluster.trace", "cluster.flame"}
        for h in cluster_hops:
            assert h["emitted"] == h["delivered"] + h["dropped_total"], h
            assert h["in_flight"] == 0, h
        # the degraded queries above dropped frames with a reason
        assert sum(h["dropped_total"] for h in cluster_hops) > 0
        assert any("error" in h["dropped"] or "timeout" in h["dropped"]
                   for h in cluster_hops)
    finally:
        for s in [solo] + shards:
            try:
                s.stop()
            except Exception:
                pass
