"""Native (C++) component tests. Skip cleanly when the toolchain is absent."""

import pytest

native = pytest.importorskip("deepflow_tpu.native")

if not native.available():
    pytest.skip("libdfnative.so not buildable here", allow_module_level=True)


def test_native_dict_roundtrip():
    d = native.NativeDict()
    ids = d.encode_many(["", "a", "b", "a", "c"])
    assert ids.tolist() == [0, 1, 2, 1, 3]
    assert len(d) == 4
    assert d.decode(2) == "b"
    assert d.lookup("c") == 3
    assert d.lookup("zz") is None
    d.load_entries(["x", "a"])  # load dedups against existing
    assert d.lookup("x") == 4
    assert len(d) == 5


def test_native_decode_matches_python():
    from tests.test_flow import eth_tcp_frame
    from deepflow_tpu.agent.packet import TcpFlags, decode_ethernet

    frames = [
        eth_tcp_frame("1.2.3.4", "5.6.7.8", 1234, 80,
                      TcpFlags.SYN | TcpFlags.ACK, seq=42, ack=7),
        eth_tcp_frame("9.9.9.9", "8.8.8.8", 53, 4444, TcpFlags.PSH,
                      payload=b"hello world"),
        b"\x00" * 20,  # junk: native must flag not-ok
    ]
    recs, ok = native.decode_eth_batch(frames)
    assert ok.tolist() == [True, True, False]
    for i in (0, 1):
        mp = decode_ethernet(frames[i])
        assert int(recs[i]["port_src"]) == mp.port_src
        assert int(recs[i]["port_dst"]) == mp.port_dst
        assert int(recs[i]["tcp_flags"]) == mp.tcp_flags
        assert int(recs[i]["seq"]) == mp.seq
        assert int(recs[i]["ip_src"]).to_bytes(4, "big") == mp.ip_src
        po, pl = int(recs[i]["payload_off"]), int(recs[i]["payload_len"])
        assert frames[i][po:po + pl] == mp.payload


def test_read_pcap_native_equals_python(tmp_path):
    from tests.test_flow import eth_tcp_frame, write_pcap
    from deepflow_tpu.agent.packet import TcpFlags, read_pcap

    frames = [eth_tcp_frame("10.0.0.1", "10.0.0.2", 40000 + i, 80,
                            TcpFlags.PSH | TcpFlags.ACK,
                            payload=b"x" * i, seq=i) for i in range(50)]
    p = str(tmp_path / "t.pcap")
    write_pcap(p, frames)
    a = read_pcap(p, use_native=True)
    b = read_pcap(p, use_native=False)
    assert len(a) == len(b) == 50
    for x, y in zip(a, b):
        assert (x.ip_src, x.port_src, x.seq, x.payload, x.packet_len) == \
               (y.ip_src, y.port_src, y.seq, y.payload, y.packet_len)


def test_l4_column_decoder_matches_pb():
    """The native columnar wire decoder must agree field-for-field with
    protobuf on a fully-populated batch, report l7 segment offsets, and
    reject garbage (fallback contract)."""
    import socket

    import pytest

    from deepflow_tpu import native
    from deepflow_tpu.proto import pb

    try:
        dec = native.L4ColumnDecoder()
    except RuntimeError:
        pytest.skip("libdfnative.so unavailable")
    batch = pb.FlowLogBatch()
    for i in range(50):
        f = batch.l4.add()
        f.flow_id = 1000 + i
        f.key.ip_src = socket.inet_aton(f"10.1.{i}.2")
        f.key.ip_dst = socket.inet_aton("10.9.9.9")
        f.key.port_src = 40000 + i
        f.key.port_dst = 443
        f.key.proto = 1
        f.key.tap_port = 3
        f.key.tunnel_type = 1
        f.key.tunnel_id = 7777
        f.start_time_ns = 10**18 + i
        f.end_time_ns = 10**18 + i + 500
        f.packet_tx = 11; f.packet_rx = 12
        f.byte_tx = 13; f.byte_rx = 14
        f.l7_request = 2; f.l7_response = 1
        f.rtt_us = 150; f.art_us = 250
        f.retrans_tx = 1; f.retrans_rx = 2
        f.zero_win_tx = 3; f.zero_win_rx = 4
        f.close_type = "timeout"
        f.syn_count = 1; f.synack_count = 1
        f.gpid_0 = 42; f.gpid_1 = 43
        f.pod_0 = f"pod-{i}"
    l7 = batch.l7.add()
    l7.flow_id = 9; l7.request_type = "GET"
    payload = batch.SerializeToString()
    res = dec.decode(payload)
    assert res is not None
    n, cols, l7segs, arena = res
    assert n == 50
    for i, f in enumerate(batch.l4):
        assert cols["flow_id"][i] == f.flow_id
        assert cols["start_time_ns"][i] == f.start_time_ns
        assert cols["end_time_ns"][i] == f.end_time_ns
        assert cols["ip4_src"][i] == int.from_bytes(f.key.ip_src, "big")
        assert cols["port_src"][i] == f.key.port_src
        assert cols["proto"][i] == 1
        assert cols["tap_port"][i] == 3
        assert cols["tunnel_type"][i] == 1
        assert cols["tunnel_id"][i] == 7777
        assert cols["rtt_us"][i] == 150 and cols["art_us"][i] == 250
        assert cols["close_type"][i] == 3  # timeout
        assert cols["gpid_0"][i] == 42 and cols["gpid_1"][i] == 43
        ab = bytes(arena)
        o, ln = int(cols["pod0_off"][i]), int(cols["pod0_len"][i])
        assert ab[o:o + ln].decode() == f"pod-{i}"
    assert len(l7segs) == 1
    o, ln = l7segs[0]
    assert pb.L7FlowLog.FromString(payload[o:o + ln]).request_type == "GET"
    # v6 rows flagged, not dropped
    b6 = pb.FlowLogBatch()
    f6 = b6.l4.add()
    f6.key.ip_src = b"\x20\x01" + b"\x00" * 14
    f6.key.ip_dst = socket.inet_aton("10.0.0.1")
    res6 = dec.decode(b6.SerializeToString())
    assert res6 is not None and res6[1]["is_v6"][0] == 1
    # malformed input -> None (python fallback), never a crash
    assert dec.decode(b"\xff" * 40) is None
