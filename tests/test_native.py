"""Native (C++) component tests. Skip cleanly when the toolchain is absent."""

import pytest

native = pytest.importorskip("deepflow_tpu.native")

if not native.available():
    pytest.skip("libdfnative.so not buildable here", allow_module_level=True)


def test_native_dict_roundtrip():
    d = native.NativeDict()
    ids = d.encode_many(["", "a", "b", "a", "c"])
    assert ids.tolist() == [0, 1, 2, 1, 3]
    assert len(d) == 4
    assert d.decode(2) == "b"
    assert d.lookup("c") == 3
    assert d.lookup("zz") is None
    d.load_entries(["x", "a"])  # load dedups against existing
    assert d.lookup("x") == 4
    assert len(d) == 5


def test_native_decode_matches_python():
    from tests.test_flow import eth_tcp_frame
    from deepflow_tpu.agent.packet import TcpFlags, decode_ethernet

    frames = [
        eth_tcp_frame("1.2.3.4", "5.6.7.8", 1234, 80,
                      TcpFlags.SYN | TcpFlags.ACK, seq=42, ack=7),
        eth_tcp_frame("9.9.9.9", "8.8.8.8", 53, 4444, TcpFlags.PSH,
                      payload=b"hello world"),
        b"\x00" * 20,  # junk: native must flag not-ok
    ]
    recs, ok = native.decode_eth_batch(frames)
    assert ok.tolist() == [True, True, False]
    for i in (0, 1):
        mp = decode_ethernet(frames[i])
        assert int(recs[i]["port_src"]) == mp.port_src
        assert int(recs[i]["port_dst"]) == mp.port_dst
        assert int(recs[i]["tcp_flags"]) == mp.tcp_flags
        assert int(recs[i]["seq"]) == mp.seq
        assert int(recs[i]["ip_src"]).to_bytes(4, "big") == mp.ip_src
        po, pl = int(recs[i]["payload_off"]), int(recs[i]["payload_len"])
        assert frames[i][po:po + pl] == mp.payload


def test_read_pcap_native_equals_python(tmp_path):
    from tests.test_flow import eth_tcp_frame, write_pcap
    from deepflow_tpu.agent.packet import TcpFlags, read_pcap

    frames = [eth_tcp_frame("10.0.0.1", "10.0.0.2", 40000 + i, 80,
                            TcpFlags.PSH | TcpFlags.ACK,
                            payload=b"x" * i, seq=i) for i in range(50)]
    p = str(tmp_path / "t.pcap")
    write_pcap(p, frames)
    a = read_pcap(p, use_native=True)
    b = read_pcap(p, use_native=False)
    assert len(a) == len(b) == 50
    for x, y in zip(a, b):
        assert (x.ip_src, x.port_src, x.seq, x.payload, x.packet_len) == \
               (y.ip_src, y.port_src, y.seq, y.payload, y.packet_len)
