"""Kafka exporter tests against an in-process stub broker.

The stub decodes requests with its own struct unpacking (independent of
deepflow_tpu.utils.kafkawire's builders), verifies message CRCs, and
answers Metadata v0 / Produce v2 like a single-node broker would.
"""

import json
import socket
import struct
import threading
import time
import zlib

import pytest

from deepflow_tpu.server.exporters import KafkaExporter
from deepflow_tpu.utils import kafkawire as kw


class StubBroker(threading.Thread):
    def __init__(self, n_partitions: int = 2, produce_errors=None):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.n_partitions = n_partitions
        self.produce_errors = list(produce_errors or [])
        self.messages: dict[int, list[bytes]] = {}
        self.crc_failures = 0
        self.api_versions_seen: list[tuple[int, int]] = []
        self._stop = False

    def run(self) -> None:
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def stop(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass

    def _recv_exact(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _handle(self, conn) -> None:
        try:
            while True:
                hdr = self._recv_exact(conn, 4)
                if hdr is None:
                    return
                size = struct.unpack(">i", hdr)[0]
                data = self._recv_exact(conn, size)
                if data is None:
                    return
                api_key, api_ver, corr = struct.unpack(">hhi", data[:8])
                self.api_versions_seen.append((api_key, api_ver))
                pos = 8
                cid_len = struct.unpack(">h", data[pos:pos + 2])[0]
                pos += 2 + max(cid_len, 0)
                if api_key == 3:
                    conn.sendall(self._metadata_response(corr, data[pos:]))
                elif api_key == 0:
                    conn.sendall(self._produce_response(corr, data[pos:]))
                else:
                    return
        except OSError:
            pass
        finally:
            conn.close()

    def _metadata_response(self, corr: int, body: bytes) -> bytes:
        (n_topics,) = struct.unpack(">i", body[:4])
        pos = 4
        topics = []
        for _ in range(n_topics):
            tlen = struct.unpack(">h", body[pos:pos + 2])[0]
            topics.append(body[pos + 2:pos + 2 + tlen].decode())
            pos += 2 + tlen
        out = struct.pack(">i", 1)  # one broker: us
        out += struct.pack(">i", 0)
        host = b"127.0.0.1"
        out += struct.pack(">h", len(host)) + host
        out += struct.pack(">i", self.port)
        out += struct.pack(">i", len(topics))
        for t in topics:
            out += struct.pack(">h", 0)  # topic error
            tb = t.encode()
            out += struct.pack(">h", len(tb)) + tb
            out += struct.pack(">i", self.n_partitions)
            for pid in range(self.n_partitions):
                out += struct.pack(">hiii", 0, pid, 0, 1)  # leader=0
                out += struct.pack(">i", 0)                # replicas[0]
                out += struct.pack(">i", 1)                # isr count
                out += struct.pack(">i", 0)
        payload = struct.pack(">i", corr) + out
        return struct.pack(">i", len(payload)) + payload

    def _produce_response(self, corr: int, body: bytes) -> bytes:
        acks, timeout_ms, n_topics = struct.unpack(">hii", body[:10])
        assert acks == 1 and n_topics == 1
        pos = 10
        tlen = struct.unpack(">h", body[pos:pos + 2])[0]
        topic = body[pos + 2:pos + 2 + tlen].decode()
        pos += 2 + tlen
        (n_parts,) = struct.unpack(">i", body[pos:pos + 4])
        assert n_parts == 1
        pos += 4
        partition, set_size = struct.unpack(">ii", body[pos:pos + 8])
        pos += 8
        msg_set = body[pos:pos + set_size]
        # walk the message set: offset i64, size i32, crc u32, magic, attrs,
        # timestamp i64, key bytes, value bytes
        mpos = 0
        base = len(self.messages.get(partition, []))
        while mpos < len(msg_set):
            _, msize = struct.unpack(">qi", msg_set[mpos:mpos + 12])
            msg = msg_set[mpos + 12:mpos + 12 + msize]
            (crc,) = struct.unpack(">I", msg[:4])
            if zlib.crc32(msg[4:]) & 0xFFFFFFFF != crc:
                self.crc_failures += 1
            magic, attrs = struct.unpack(">bb", msg[4:6])
            assert magic == 1 and attrs == 0
            p = 6 + 8  # skip timestamp
            (klen,) = struct.unpack(">i", msg[p:p + 4])
            p += 4 + max(klen, 0)
            (vlen,) = struct.unpack(">i", msg[p:p + 4])
            value = msg[p + 4:p + 4 + vlen]
            self.messages.setdefault(partition, []).append(value)
            mpos += 12 + msize
        err = self.produce_errors.pop(0) if self.produce_errors else 0
        tb = topic.encode()
        out = struct.pack(">i", 1)
        out += struct.pack(">h", len(tb)) + tb
        out += struct.pack(">i", 1)
        out += struct.pack(">ihqq", partition, err, base, -1)
        out += struct.pack(">i", 0)  # throttle_time_ms
        payload = struct.pack(">i", corr) + out
        return struct.pack(">i", len(payload)) + payload


def wait_for(pred, timeout=5.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_wire_message_set_roundtrip():
    msgs = [(None, b'{"a": 1}', 123), (b"key", b'{"b": 2}', 456)]
    data = kw.message_set(msgs)
    # decode independently
    pos, seen = 0, []
    while pos < len(data):
        _, msize = struct.unpack(">qi", data[pos:pos + 12])
        msg = data[pos + 12:pos + 12 + msize]
        (crc,) = struct.unpack(">I", msg[:4])
        assert zlib.crc32(msg[4:]) & 0xFFFFFFFF == crc
        p = 6 + 8
        (klen,) = struct.unpack(">i", msg[p:p + 4])
        key = msg[p + 4:p + 4 + klen] if klen >= 0 else None
        p += 4 + max(klen, 0)
        (vlen,) = struct.unpack(">i", msg[p:p + 4])
        seen.append((key, msg[p + 4:p + 4 + vlen]))
        pos += 12 + msize
    assert seen == [(None, b'{"a": 1}'), (b"key", b'{"b": 2}')]


def test_exporter_ships_to_stub_broker():
    broker = StubBroker(n_partitions=2)
    broker.start()
    try:
        exp = KafkaExporter(f"kafka://127.0.0.1:{broker.port}/flows",
                            batch_size=4, flush_interval_s=0.1).start()
        try:
            rows = [{"flow_id": i, "byte_tx": i * 100} for i in range(8)]
            exp.feed("flow_log.l4_flow_log", rows[:4])
            assert wait_for(lambda: sum(
                len(v) for v in broker.messages.values()) >= 4)
            exp.feed("flow_log.l4_flow_log", rows[4:])
            assert wait_for(lambda: sum(
                len(v) for v in broker.messages.values()) >= 8)
        finally:
            exp.stop()
        assert broker.crc_failures == 0
        got = [json.loads(v) for vs in broker.messages.values() for v in vs]
        assert {g["flow_id"] for g in got} == set(range(8))
        assert all(g["table"] == "flow_log.l4_flow_log" for g in got)
        # round-robin used both partitions
        assert len(broker.messages) == 2
        assert exp.stats["exported"] == 8 and exp.stats["errors"] == 0
        # protocol versions: metadata v0, produce v2
        assert (3, 0) in broker.api_versions_seen
        assert (0, 2) in broker.api_versions_seen
    finally:
        broker.stop()


def test_exporter_retries_retriable_broker_error():
    # first produce gets NOT_LEADER_FOR_PARTITION; retry must re-discover
    # metadata and succeed
    broker = StubBroker(n_partitions=1, produce_errors=[6])
    broker.start()
    try:
        exp = KafkaExporter(f"kafka://127.0.0.1:{broker.port}/flows",
                            batch_size=2, flush_interval_s=0.1,
                            max_retries=2).start()
        try:
            exp.feed("t", [{"x": 1}, {"x": 2}])
            assert wait_for(lambda: len(broker.messages.get(0, [])) >= 2)
        finally:
            exp.stop()
        assert exp.stats["errors"] == 1
        assert exp.stats["exported"] == 2
    finally:
        broker.stop()


def test_endpoint_validation():
    with pytest.raises(ValueError):
        KafkaExporter("http://host:9092/topic")
    with pytest.raises(ValueError):
        KafkaExporter("kafka://host:9092")  # no topic


def test_exporters_api_kafka():
    import urllib.request

    from deepflow_tpu.server import Server
    broker = StubBroker()
    broker.start()
    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        base = f"http://127.0.0.1:{server.query_port}"
        req = urllib.request.Request(
            f"{base}/v1/exporters",
            data=json.dumps({
                "type": "kafka",
                "endpoint": f"kafka://127.0.0.1:{broker.port}/telemetry",
            }).encode())
        out = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert out["added"] == "kafka"
        assert any("KafkaExporter" in k for k in out["exporters"])
        # bad endpoint is a clean 400
        req = urllib.request.Request(
            f"{base}/v1/exporters",
            data=json.dumps({"type": "kafka",
                             "endpoint": "kafka://x"}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400
    finally:
        server.stop()
        broker.stop()
