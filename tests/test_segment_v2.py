"""Segment format v2 (ISSUE 11): cross-version golden read matrix,
delta/FoR/dictrank codecs, bloom + inline-id skip indexes, native
filter/gather parity, and crash-restart convergence of the
migrate-on-compact path."""

import os
import subprocess
import sys

import numpy as np
import pytest

from deepflow_tpu.query import execute
from deepflow_tpu.store import Database
from deepflow_tpu.store.dictionary import Dictionary
from deepflow_tpu.store.segment import (
    Segment, _bloom_build, _bloom_maybe, _bloom_params, choose_codec,
    write_segment)

TABLE = "application_log.log"


# -- codecs ------------------------------------------------------------------

def test_delta_codec_roundtrip(tmp_path):
    """Monotone u64 ns timestamps pack as zigzag deltas and round-trip
    byte-identically — including a backwards step (late row)."""
    t = np.cumsum(np.full(4096, 1_000_000, dtype=np.uint64)) \
        + np.uint64(1_754_000_000_000_000_000)
    t[100] -= np.uint64(2_000_000)  # non-monotone wrinkle
    p = str(tmp_path / "seg.seg")
    footer = write_segment(p, {"time": t}, fmt=2)
    assert footer["cols"]["time"]["codec"] == "delta"
    assert footer["cols"]["time"]["nbytes"] < t.nbytes // 2
    out = Segment.open(p).chunk()["time"]
    assert out.dtype == np.uint64
    assert np.array_equal(out, t)


def test_for_codec_roundtrip_signed_and_extremes(tmp_path):
    """Frame-of-reference narrows a tight range at any offset; extreme
    u64 values and wide ranges fall back to raw/zlib, never corrupt."""
    rng = np.random.default_rng(3)
    # offsets span 60k (FoR width 2) but jump wildly row to row
    # (zigzag deltas need width 4), so frame-of-reference must win
    near_max = (np.uint64(2**64 - 70_000)
                + rng.integers(0, 60_000, 4096).astype(np.uint64))
    neg = rng.integers(-5_000_000, -4_940_000, 4096).astype(np.int64)
    wide = rng.integers(0, 2**63, 4096, dtype=np.uint64)
    p = str(tmp_path / "seg.seg")
    footer = write_segment(
        p, {"near_max": near_max, "neg": neg, "wide": wide}, fmt=2)
    assert footer["cols"]["near_max"]["codec"] == "for"
    assert footer["cols"]["neg"]["codec"] == "for"
    assert footer["cols"]["wide"]["codec"] in ("raw", "zlib")
    out = Segment.open(p).chunk()
    assert np.array_equal(out["near_max"], near_max)
    assert np.array_equal(out["neg"], neg)
    assert np.array_equal(out["wide"], wide)


def test_choose_codec_is_observable():
    """Satellite 3: ONE codec decision point, and it reports what it
    chose — counts + timing flow to the tier snapshot / cost model."""
    arr = np.cumsum(np.full(2048, 7, dtype=np.uint64))
    raw = memoryview(np.ascontiguousarray(arr)).cast("B")
    codec, meta, blob = choose_codec(
        "t", arr, raw, fmt=2, compress=True,
        zone=(int(arr.min()), int(arr.max())), codec_hints=None)
    assert codec == "delta"
    # the writer threads counts/observe through for every column
    counts = {}
    seen = []
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        write_segment(os.path.join(d, "s.seg"),
                      {"t": arr, "c": np.zeros(2048, dtype=np.uint32)},
                      fmt=2, codec_counts=counts,
                      observe=lambda c, n, ns: seen.append((c, n)))
    assert counts == {"delta": 1, "const": 1}
    assert sorted(seen) == [("const", 2048), ("delta", 2048)]


def test_dictrank_rewrite_and_zstr(tmp_path):
    """Compaction-grade writes rewrite dictionary columns to collation
    rank order: ids decode back unchanged, zone maps become real string
    ranges (zstr), and the zmin/zmax of the stored ids are ranks."""
    d = Dictionary()
    words = ["pear", "apple", "zebra", "mango", "kiwi"]
    ids = np.array([d.encode(w) for w in words] * 40, dtype=np.uint32)
    p = str(tmp_path / "seg.seg")
    footer = write_segment(p, {"svc": ids}, fmt=2, level=1,
                           dict_gens={"svc": (0, 1)}, dicts={"svc": d})
    ent = footer["cols"]["svc"]
    assert ent["codec"] == "dictrank"
    assert ent["zstr"][0] == "apple" and ent["zstr"][1] == "zebra"
    seg = Segment.open(p)
    assert seg.str_zone("svc") == ("apple", "zebra")
    out = seg.chunk()["svc"]
    assert np.array_equal(out, ids)  # decode restores ORIGINAL ids
    assert [d.decode(int(s)) for s in out[:5]] == words


# -- skip indexes ------------------------------------------------------------

def test_inline_id_index_exact(tmp_path):
    """<= 64 distinct ids stores the exact sorted id list: membership
    answers are never wrong in either direction."""
    ids = np.array([3, 9, 9, 3, 17] * 100, dtype=np.uint32)
    p = str(tmp_path / "seg.seg")
    footer = write_segment(p, {"svc": ids}, fmt=2, level=1,
                           dict_gens={"svc": (0, 1)})
    assert footer["cols"]["svc"]["ids"] == [3, 9, 17]
    seg = Segment.open(p)
    assert seg.has_index("svc")
    assert seg.maybe_contains("svc", [9])
    assert seg.maybe_contains("svc", [2, 17])
    assert not seg.maybe_contains("svc", [2, 4, 1000])


def test_bloom_index_sound_and_tight(tmp_path):
    """Bloom soundness: NEVER a false negative for a present id (that
    would drop rows from answers); false-positive rate stays well under
    1% at 12 bits/key, k=6."""
    present = np.arange(0, 20_000, 2, dtype=np.uint32)  # 10k even ids
    bits = np.frombuffer(_bloom_build(present), dtype=np.uint8)
    m = _bloom_params(len(present))
    assert all(_bloom_maybe(bits, m, int(s)) for s in present[:2000])
    absent = np.arange(1, 20_001, 2, dtype=np.uint32)[:4000]  # odd ids
    fp = sum(_bloom_maybe(bits, m, int(s)) for s in absent)
    assert fp / len(absent) < 0.01

    # and end to end: a high-cardinality column gets the bloom entry
    ids = np.arange(5000, dtype=np.uint32)
    p = str(tmp_path / "seg.seg")
    footer = write_segment(p, {"trace": ids}, fmt=2, level=1,
                           dict_gens={"trace": (0, 1)})
    assert "bloom" in footer["cols"]["trace"]
    seg = Segment.open(p)
    assert seg.maybe_contains("trace", [4999])
    assert sum(seg.maybe_contains("trace", [i])
               for i in range(6000, 7000)) < 10


def test_flush_grade_skips_indexes(tmp_path):
    """level 0 (flusher, beside the ingest hot path) builds no skip
    indexes; columns report no index and maybe_contains stays True."""
    ids = np.arange(5000, dtype=np.uint32)
    p = str(tmp_path / "seg.seg")
    footer = write_segment(p, {"trace": ids}, fmt=2, level=0,
                           dict_gens={"trace": (0, 1)})
    assert "bloom" not in footer["cols"]["trace"]
    seg = Segment.open(p)
    assert not seg.has_index("trace")
    assert seg.maybe_contains("trace", [999_999])


def test_lazy_chunk_decodes_on_touch(tmp_path):
    """A LazyChunk decodes only the columns a scan reads — a pruned or
    empty-survivor segment costs zero decode for the rest."""
    p = str(tmp_path / "seg.seg")
    write_segment(p, {"a": np.arange(1000, dtype=np.uint64),
                      "b": (np.arange(1000, dtype=np.uint64) * 37) % 11,
                      "c": np.arange(1000, dtype=np.uint32)}, fmt=2)
    seg = Segment.open(p)
    ch = seg.chunk()
    assert ch.rows == 1000
    assert not seg._cache  # opening decodes nothing
    np.testing.assert_array_equal(ch["a"], np.arange(1000))
    assert set(seg._cache) == {"a"}  # touching a decoded ONLY a


# -- cross-version golden read matrix ----------------------------------------

def _chunk(n=500, t0=1_754_000_000_000_000_000):
    i = np.arange(n, dtype=np.uint64)
    return {"time": t0 + i * 1_000_000,
            "svc": (i % 7).astype(np.uint32),
            "dur": (1000 + i * 37 % 5000).astype(np.uint64)}


def test_v1_written_v2_read_byte_identical(tmp_path):
    """The frozen v1 writer's output reads back byte-identical to the
    same chunk through the v2 writer — v1 stays readable forever."""
    ch = _chunk()
    p1, p2 = str(tmp_path / "v1.seg"), str(tmp_path / "v2.seg")
    write_segment(p1, ch, time_col="time", fmt=1)
    write_segment(p2, ch, time_col="time", fmt=2)
    s1, s2 = Segment.open(p1), Segment.open(p2)
    assert (s1.fmt, s2.fmt) == (1, 2)
    assert (s1.tmin, s1.tmax) == (s2.tmin, s2.tmax)
    c1, c2 = s1.chunk(), s2.chunk()
    for name in ch:
        assert np.array_equal(c1[name], ch[name]), name
        assert np.array_equal(c2[name], ch[name]), name


def test_env_pin_yields_to_explicit_fmt(tmp_path, monkeypatch):
    """DF_SEG_FORMAT only steers fmt=None callers (whole-process pin);
    an explicit fmt wins — this is what makes migrate-on-compact
    converge even in a v1-pinned process."""
    monkeypatch.setenv("DF_SEG_FORMAT", "1")
    ch = _chunk(50)
    pd, pe = str(tmp_path / "d.seg"), str(tmp_path / "e.seg")
    write_segment(pd, ch)            # fmt=None -> env pin -> v1
    write_segment(pe, ch, fmt=2)     # explicit -> v2 regardless
    assert Segment.open(pd).fmt == 1
    assert Segment.open(pe).fmt == 2


def _seed_db(data_dir, n_flushes=6, rows=200, v1=False):
    if v1:
        os.environ["DF_SEG_FORMAT"] = "1"
    try:
        db = Database(data_dir=data_dir, storage=True, chunk_rows=rows)
        t = db.table(TABLE)
        for s in range(n_flushes):
            base = s * rows
            t.append_rows([
                {"time": (base + j) * 1_000_000,
                 "app_service": f"svc-{(base + j) % 5}",
                 "severity_number": (base + j) % 24 + 1,
                 "trace_id": f"{(base + j) * 2654435761 % 2**32:08x}",
                 "body": f"m{(base + j) % 9}"}
                for j in range(rows)])
            t.flush()
            db.flush_to_tier()
    finally:
        os.environ.pop("DF_SEG_FORMAT", None)
    return db


_GOLDEN_SQL = [
    "SELECT app_service, Count(*) AS c, Sum(severity_number) AS s "
    "FROM log GROUP BY app_service ORDER BY app_service",
    "SELECT Count(*) AS c FROM log WHERE trace_id = '9908b100'",
    "SELECT Count(*) AS c FROM log WHERE app_service >= 'svc-3'",
    "SELECT Max(time) AS t FROM log WHERE severity_number = 7",
]


def _answers(db):
    t = db.table(TABLE)
    return [execute(t, s).values for s in _GOLDEN_SQL]


def test_mixed_manifest_and_migration_equality(tmp_path):
    """v1-only, mixed v1+v2, and fully-migrated tiers all answer the
    golden queries byte-identically; compaction leaves zero v1
    segments and the manifest survives a reopen."""
    d = str(tmp_path / "db")
    db = _seed_db(d, n_flushes=4, v1=True)
    golden = _answers(db)

    # mixed manifest: append v2 flushes beside the v1 segments
    t = db.table(TABLE)
    t.append_rows([
        {"time": 10**15 + j, "app_service": f"svc-{j % 5}",
         "severity_number": j % 24 + 1, "trace_id": f"x{j:07d}",
         "body": "late"} for j in range(100)])
    t.flush()
    db.flush_to_tier()
    fmts = {s.fmt for s in db.tier_store.tier(TABLE).segments()}
    assert fmts == {1, 2}
    mixed = _answers(db)
    assert mixed[0][0][1] > golden[0][0][1]  # new rows visible

    res = db.compact_tier()
    assert res["runs_built"] >= 1
    assert db.tier_store.migrate_v1_remaining() == 0
    assert {s.fmt for s in db.tier_store.tier(TABLE).segments()} == {2}
    assert _answers(db) == mixed  # byte-identical across the migration

    db2 = Database(data_dir=d, storage=True)
    assert _answers(db2) == mixed  # and across a restart


def test_compacted_runs_are_sorted_and_ranked(tmp_path):
    """Compaction output: time-sorted runs with delta-coded time,
    dictrank string columns, and skip indexes the planner can use."""
    db = _seed_db(str(tmp_path / "db"), n_flushes=5, v1=True)
    db.compact_tier()
    segs = db.tier_store.tier(TABLE).segments()
    assert segs
    for s in segs:
        assert s.fmt == 2 and s.run is not None
        assert s.sorted_by == "time"
        ch = s.chunk()
        tcol = np.asarray(ch["time"])
        assert (tcol[1:] >= tcol[:-1]).all()
        codecs = s.codecs()
        assert codecs["time"] in ("delta", "for")
        assert codecs["app_service"] == "dictrank"
        assert s.has_index("trace_id")
        assert s.str_zone("app_service") is not None


def _crash_compact(data_dir, mode, pin_v1=False):
    env = {k: v for k, v in os.environ.items() if k != "DF_SEG_FORMAT"}
    env["DF_COMPACT_CRASH"] = mode
    env["JAX_PLATFORMS"] = "cpu"
    if pin_v1:
        env["DF_SEG_FORMAT"] = "1"
    child = ("from deepflow_tpu.store.db import Database\n"
             f"Database({data_dir!r}, storage=True).compact_tier()\n")
    return subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, timeout=120)


def test_restart_mid_compaction_converges(tmp_path):
    """Crash AFTER the new run files are staged but BEFORE the manifest
    commit: reopen serves the old segments byte-identically (the staged
    run is garbage-collected) and the next compaction converges."""
    d = str(tmp_path / "db")
    golden = _answers(_seed_db(d, v1=True))
    proc = _crash_compact(d, "after_stage")
    assert proc.returncode == 43, proc.stderr.decode()[-500:]
    db = Database(data_dir=d, storage=True)
    assert db.tier_store.migrate_v1_remaining() > 0  # commit never ran
    assert _answers(db) == golden
    db.compact_tier()
    assert db.tier_store.migrate_v1_remaining() == 0
    assert _answers(db) == golden


def test_restart_mid_migration_converges(tmp_path):
    """Crash AFTER the manifest commit but BEFORE the replaced v1
    segments unlink: reopen serves the new runs, deletes the orphaned
    victims, and answers stay byte-identical — even when the retrying
    process is pinned to DF_SEG_FORMAT=1."""
    d = str(tmp_path / "db")
    golden = _answers(_seed_db(d, v1=True))
    proc = _crash_compact(d, "after_commit", pin_v1=True)
    assert proc.returncode == 43, proc.stderr.decode()[-500:]
    db = Database(data_dir=d, storage=True)
    assert db.tier_store.migrate_v1_remaining() == 0  # commit landed
    assert _answers(db) == golden
    res = db.compact_tier()  # idempotent: nothing left to migrate
    assert res["segments_replaced"] == 0
    assert _answers(db) == golden


# -- native filter/gather kernels --------------------------------------------

@pytest.fixture
def nat():
    from deepflow_tpu import native
    if not native.available():
        pytest.skip("native library unavailable")
    return native


def test_native_sel_range_parity(nat):
    """df_qx_sel_cmp matches the numpy mask for every int width and
    signedness, including negative bounds and u64 extremes."""
    rng = np.random.default_rng(11)
    for dt in (np.uint8, np.int8, np.uint16, np.int16,
               np.uint32, np.int32, np.uint64, np.int64):
        info = np.iinfo(dt)
        col = rng.integers(info.min, info.max, 10_000,
                           dtype=dt, endpoint=True)
        for lo, hi in ((info.min, info.max),
                       (info.min, info.min),
                       (int(col[5]), int(col[5])),
                       (info.max // 2, info.max)):
            idx = nat.qx_sel_range(col, lo, hi)
            assert idx is not None, dt
            ref = np.nonzero((col >= dt(lo)) & (col <= dt(hi)))[0]
            assert np.array_equal(idx, ref.astype(np.uint64)), (dt, lo, hi)


def test_native_sel_isin_and_gather_parity(nat):
    rng = np.random.default_rng(12)
    col = rng.integers(0, 5000, 50_000).astype(np.uint32)
    wanted = np.array([3, 999, 4999, 7777], dtype=np.uint32)
    idx = nat.qx_sel_isin(col, wanted)
    ref = np.nonzero(np.isin(col, wanted))[0].astype(np.uint64)
    assert np.array_equal(idx, ref)
    assert np.array_equal(np.diff(idx.astype(np.int64)) > 0,
                          np.full(len(idx) - 1, True))  # ascending
    for dt in (np.uint8, np.uint16, np.uint32, np.uint64, np.int64):
        src = rng.integers(0, 200, 50_000).astype(dt)
        out = nat.qx_gather(src, idx)
        assert np.array_equal(out, src[idx])


def test_selective_filter_matches_fallback(tmp_path, monkeypatch):
    """The index-list filter path (native kernels) and the DF_NO_NATIVE
    numpy mask path return byte-identical answers over a compacted
    tier."""
    db = _seed_db(str(tmp_path / "db"), v1=True)
    db.compact_tier()
    fast = _answers(db)
    monkeypatch.setenv("DF_NO_NATIVE", "1")
    assert _answers(db) == fast
