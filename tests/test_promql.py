"""PromQL subset + Tempo API tests."""

import json
import urllib.request

import pytest

from deepflow_tpu.query import promql
from deepflow_tpu.store import Database


def make_db():
    db = Database()
    t = db.table("flow_metrics.network.1s")
    rows = []
    for s in range(0, 120, 10):
        for host, tx in (("h1", 100), ("h2", 50)):
            rows.append({"time": 1000 + s, "ip_src": "1.1.1.1",
                         "ip_dst": "2.2.2.2", "server_port": 80,
                         "protocol": 1, "byte_tx": tx, "host": host})
    t.append_rows(rows)
    return db


def test_parse():
    q = promql.parse('rate(flow_metrics_network_byte_tx{host="h1"}[1m])')
    assert isinstance(q, promql.Call) and q.fn == "rate"
    m = q.args[0]
    assert isinstance(m, promql.MatrixSelector) and m.range_s == 60
    assert m.vs.matchers == [("host", "=", "h1")]

    q2 = promql.parse(
        'sum by (host) (rate(flow_metrics_network_byte_tx[30s])) * 8')
    assert isinstance(q2, promql.BinOp) and q2.op == "*"
    assert isinstance(q2.lhs, promql.Agg)
    assert q2.lhs.op == "sum" and q2.lhs.grouping == ["host"]
    assert isinstance(q2.rhs, promql.Num) and q2.rhs.value == 8

    with pytest.raises(promql.PromqlError):
        promql.parse("rate(foo)")  # needs [range]
    with pytest.raises(promql.PromqlError):
        promql.parse("foo{")


def test_instant_series_and_matchers():
    db = make_db()
    out = promql.evaluate(
        db, 'flow_metrics_network_byte_tx{host="h1"}', 1000, 1120, 30)
    assert len(out) == 1
    assert out[0]["metric"]["host"] == "h1"
    assert all(v == 100.0 for _, v in out[0]["values"])

    out = promql.evaluate(
        db, 'flow_metrics_network_byte_tx{host!="h1"}', 1000, 1120, 30)
    assert len(out) == 1 and out[0]["metric"]["host"] == "h2"

    out = promql.evaluate(
        db, 'flow_metrics_network_byte_tx{host=~"h.*"}', 1000, 1120, 30)
    assert len(out) == 2


def test_rate_and_sum():
    db = make_db()
    # 100 bytes every 10s for h1 -> rate over 1m = 600/60 = 10 B/s
    out = promql.evaluate(
        db, 'rate(flow_metrics_network_byte_tx{host="h1"}[1m])',
        1060, 1120, 60)
    assert out and out[0]["values"]
    ts, v = out[0]["values"][0]
    assert v == pytest.approx(10.0)

    out = promql.evaluate(
        db, 'sum(rate(flow_metrics_network_byte_tx[1m]))', 1060, 1120, 60)
    assert out[0]["values"][0][1] == pytest.approx(15.0)  # both hosts

    out = promql.evaluate(
        db, 'sum by (host) (rate(flow_metrics_network_byte_tx[1m])) * 8',
        1060, 1120, 60)
    byhost = {s["metric"]["host"]: s["values"][0][1] for s in out}
    assert byhost["h1"] == pytest.approx(80.0)  # bits
    assert byhost["h2"] == pytest.approx(40.0)


def test_errors():
    db = make_db()
    with pytest.raises(promql.PromqlError):
        promql.evaluate(db, "unknown_metric_name", 0, 10)
    with pytest.raises(promql.PromqlError):
        promql.evaluate(db, "flow_metrics_network_nope", 0, 10)


def test_http_endpoints():
    import time as _time
    from deepflow_tpu.server import Server
    from deepflow_tpu.proto import pb

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        now = int(_time.time())
        t = server.db.table("flow_metrics.network.1s")
        t.append_rows([{"time": now - 30 + i, "byte_tx": 10, "host": "h1",
                        "ip_src": "1.1.1.1", "ip_dst": "2.2.2.2",
                        "server_port": 80, "protocol": 1}
                       for i in range(10)])
        url = (f"http://127.0.0.1:{server.query_port}/prom/api/v1/"
               f"query_range?query=flow_metrics_network_byte_tx"
               f"&start={now-60}&end={now}&step=15")
        with urllib.request.urlopen(url, timeout=5) as resp:
            out = json.loads(resp.read())
        assert out["status"] == "success"
        assert out["data"]["result"]

        # tempo trace endpoint
        l7 = server.db.table("flow_log.l7_flow_log")
        l7.append_rows([{"time": 1, "trace_id": "abc", "span_id": "s1",
                         "request_type": "GET", "endpoint": "/x",
                         "response_duration": 5, "response_status": 1,
                         "l7_protocol": 1, "flow_id": 1}])
        url = f"http://127.0.0.1:{server.query_port}/api/traces/abc"
        with urllib.request.urlopen(url, timeout=5) as resp:
            out = json.loads(resp.read())
        spans = out["batches"][0]["spans"]
        assert spans[0]["operationName"] == "GET /x"
        assert spans[0]["traceID"] == "abc"
    finally:
        server.stop()


def test_integration_ingest():
    import urllib.request
    from deepflow_tpu.server import Server

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        base = f"http://127.0.0.1:{server.query_port}"
        otlp = {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": "shop"}}]},
            "scopeSpans": [{"spans": [{
                "traceId": "0af7651916cd43dd8448eb211c80319c",
                "spanId": "b7ad6b7169203331",
                "name": "GET /cart",
                "startTimeUnixNano": "1700000000000000000",
                "endTimeUnixNano": "1700000000050000000",
                "attributes": [
                    {"key": "http.method", "value": {"stringValue": "GET"}},
                    {"key": "http.status_code", "value": {"intValue": 200}}],
                "status": {"code": 1}}]}]}]}
        req = urllib.request.Request(f"{base}/api/v1/otlp/traces",
                                     data=json.dumps(otlp).encode())
        out = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert out["accepted_spans"] == 1

        # the OTLP span joins the trace view
        req = urllib.request.Request(
            f"{base}/v1/trace/Tracing",
            data=json.dumps(
                {"trace_id": "0af7651916cd43dd8448eb211c80319c"}).encode())
        tr = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert tr["result"]["span_count"] == 1
        assert tr["result"]["spans"][0]["service"] == "shop"

        # pyroscope-style folded profile upload
        folded = "main;работа;hot_loop 25\nmain;io_wait 5\nbadline\n"
        req = urllib.request.Request(
            f"{base}/api/v1/profile/ingest?name=ext-app",
            data=folded.encode())
        out = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert out["accepted_stacks"] == 2
        req = urllib.request.Request(
            f"{base}/v1/profile/ProfileTracing",
            data=json.dumps({"app_service": "ext-app"}).encode())
        flame = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert flame["result"]["total_value"] == 30

        # app log
        req = urllib.request.Request(
            f"{base}/api/v1/log",
            data=json.dumps({"service": "x", "message": "oops",
                             "level": "error"}).encode())
        out = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert out["accepted"] == 1
    finally:
        server.stop()


def test_regex_anchoring_and_enum_regex():
    db = make_db()
    t = db.table("flow_metrics.network.1s")
    t.append_rows([{"time": 1000, "byte_tx": 7, "host": "h1-backup",
                    "ip_src": "1.1.1.1", "ip_dst": "2.2.2.2",
                    "server_port": 80, "protocol": 2}])
    # anchored: h1 must NOT match h1-backup
    out = promql.evaluate(db, 'flow_metrics_network_byte_tx{host=~"h1"}',
                          1000, 1120, 30)
    hosts = {s["metric"]["host"] for s in out}
    assert hosts == {"h1"}
    # enum regex matcher works
    out = promql.evaluate(
        db, 'flow_metrics_network_byte_tx{protocol=~"ud."}', 1000, 1120, 30)
    assert out and all(s["metric"].get("protocol") == "udp" for s in out)


def test_instant_lookback_300s():
    db = Database()
    t = db.table("flow_metrics.network.1s")
    t.append_rows([{"time": 880, "byte_tx": 9, "host": "h1",
                    "ip_src": "1.1.1.1", "ip_dst": "2.2.2.2",
                    "server_port": 80, "protocol": 1}])
    # sample is 120s before start: staleness lookback must still find it
    out = promql.evaluate(db, "flow_metrics_network_byte_tx", 1000, 1060, 30)
    assert out and out[0]["values"][0][1] == 9.0


def test_self_telemetry_promql():
    """The framework observes itself: dfstats -> deepflow_system -> PromQL."""
    import time as _time
    from deepflow_tpu.agent.agent import Agent
    from deepflow_tpu.agent.config import AgentConfig
    from deepflow_tpu.server import Server

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        cfg = AgentConfig()
        cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
        cfg.profiler.enabled = False
        cfg.tpuprobe.enabled = False
        cfg.guard.enabled = False
        cfg.stats_interval_s = 0.3
        agent = Agent(cfg).start()
        _time.sleep(0.8)
        agent.stop()
        assert server.wait_for_rows("deepflow_system.deepflow_system", 1)

        now = int(_time.time())
        url = (f"http://127.0.0.1:{server.query_port}/prom/api/v1/"
               f"query_range?query=deepflow_system_agent_sender_sent_frames"
               f"&start={now-60}&end={now}&step=15")
        with urllib.request.urlopen(url, timeout=5) as resp:
            out = json.loads(resp.read())
        assert out["status"] == "success"
        series = out["data"]["result"]
        assert series and series[0]["metric"]["process"]
        assert series[0]["values"][-1][1] >= 0

        # unknown self metric is a clean error
        url = (f"http://127.0.0.1:{server.query_port}/prom/api/v1/"
               f"query_range?query=deepflow_system_nope_nope"
               f"&start={now-60}&end={now}")
        with urllib.request.urlopen(url, timeout=5) as resp:
            out = json.loads(resp.read())
        assert out["status"] == "error"
    finally:
        server.stop()


def test_self_telemetry_series_split_per_agent():
    """Two agents' identical tag_json must stay separate series via the
    universal tag columns, and host/agent_id matchers must work."""
    from deepflow_tpu.query import promql
    from deepflow_tpu.store import Database
    db = Database()
    t = db.table("deepflow_system.deepflow_system")
    now_ns = 1_700_000_000_000_000_000
    for agent_id, host, v in ((1, "h1", 10.0), (2, "h2", 20.0)):
        t.append_rows([{
            "time": now_ns, "metric_name": "agent.sender",
            "tag_json": '{"process": "python"}',
            "value_name": "sent_frames", "value": v,
            "agent_id": agent_id, "host": host}])
    out = promql.evaluate(db, "deepflow_system_agent_sender_sent_frames",
                          1_700_000_000 - 30, 1_700_000_000 + 30, 30)
    assert len(out) == 2  # one series per agent
    byhost = {s["metric"]["host"]: s["values"][-1][1] for s in out}
    assert byhost == {"h1": 10.0, "h2": 20.0}
    out = promql.evaluate(
        db, 'deepflow_system_agent_sender_sent_frames{host="h2"}',
        1_700_000_000 - 30, 1_700_000_000 + 30, 30)
    assert len(out) == 1 and out[0]["values"][-1][1] == 20.0


def test_remote_write_shared_prefix_not_shadowed():
    from deepflow_tpu.query import promql
    from deepflow_tpu.server.integration import IntegrationAPI
    from deepflow_tpu.store import Database
    from deepflow_tpu.utils import snappy
    from tests.test_remote_write import make_write_request
    import time as _time
    db = Database()
    now = int(_time.time())
    wr = make_write_request([
        ("deepflow_system_custom_up", {"k": "v"}, [((now - 5) * 1000, 1.0)])])
    IntegrationAPI(db).ingest_prometheus(snappy.compress(wr))
    out = promql.evaluate(db, "deepflow_system_custom_up", now - 10, now, 5)
    assert out and out[0]["values"][-1][1] == 1.0


def test_irate_uses_last_two_samples():
    db = Database()
    t = db.table("flow_metrics.network.1s")
    # counter-ish samples: big early value, small recent deltas
    for ts, v in ((1000, 500), (1050, 500), (1055, 10)):
        t.append_rows([{"time": ts, "byte_tx": v, "ip_src": "1.1.1.1",
                        "ip_dst": "2.2.2.2", "server_port": 80,
                        "protocol": 1, "host": "h"}])
    out = promql.evaluate(db, "irate(flow_metrics_network_byte_tx[2m])",
                          1055, 1056, 60)
    # last sample 10 over dt 5s -> 2/s (rate() over the window would differ)
    assert out[0]["values"][0][1] == pytest.approx(2.0)


def test_irate_cotimestamped_rows_no_spike():
    db = Database()
    t = db.table("flow_metrics.network.1s")
    # two rows in the SAME second for one series, then nothing newer
    rows = [{"time": ts, "byte_tx": v, "ip_src": "1.1.1.1",
             "ip_dst": "2.2.2.2", "server_port": 80, "protocol": 1,
             "host": "h"} for ts, v in ((1000, 5), (1010, 3), (1010, 7))]
    t.append_rows(rows)
    out = promql.evaluate(db, "irate(flow_metrics_network_byte_tx[2m])",
                          1010, 1011, 60)
    # (3+7) summed at t=1010, dt=10 -> 1.0/s — not a 1e9 spike
    assert out[0]["values"][0][1] == pytest.approx(1.0)


def test_multi_series_aggregates_correct():
    """Aggregates evaluate per series FIRST, then combine (round-1 bug:
    samples were pre-merged, so sum() returned a single sample, count()
    returned 1, avg(rate) returned the summed rate)."""
    db = make_db()  # two series: h1 ships 100 B/s, h2 ships 50 B/s
    # instant sum across series = 150
    out = promql.evaluate(db, "sum(flow_metrics_network_byte_tx)",
                          1000, 1120, 30)
    assert all(v == 150.0 for _, v in out[0]["values"])
    # count = number of series
    out = promql.evaluate(db, "count(flow_metrics_network_byte_tx)",
                          1000, 1120, 30)
    assert all(v == 2.0 for _, v in out[0]["values"])
    # avg = 75, min = 50, max = 100
    for agg, want in (("avg", 75.0), ("min", 50.0), ("max", 100.0)):
        out = promql.evaluate(db, f"{agg}(flow_metrics_network_byte_tx)",
                              1000, 1120, 30)
        assert all(v == want for _, v in out[0]["values"]), (agg, out)
    # avg(rate): per-series rate is tx/10s -> (10 + 5)/2 = 7.5
    # (evaluate where the 30s window holds 3 samples per series)
    out = promql.evaluate(
        db, "avg(rate(flow_metrics_network_byte_tx[30s]))", 1060, 1090, 30)
    for _, v in out[0]["values"]:
        assert v == pytest.approx((100 * 3 / 30 + 50 * 3 / 30) / 2)


def test_remote_write_counter_semantics():
    """rate()/increase()/irate() over prometheus.samples treat values as
    cumulative counters (with reset detection), not delta samples."""
    db = Database()
    t = db.table("prometheus.samples")
    base = 1_000_000
    # counter going 1000,1010,1020,... (1/s), then a reset
    rows = []
    for i, v in enumerate([1000, 1010, 1020, 1030, 5, 15]):
        rows.append({"time": base + i * 10, "metric_name": "req_total",
                     "labels_json": '{"job": "a"}', "value": float(v)})
    t.append_rows(rows)
    end = base + 50
    # window (base, base+50] holds 1010,1020,1030,5,15: raw increase =
    # 10+10 + 5(reset restart) + 10 = 35 over a 40s sampled span, then
    # Prometheus extrapolation extends 10s toward the window start:
    # 35 * 50/40 = 43.75
    out = promql.evaluate(db, "rate(req_total[50s])", end, end, 15)
    assert out[0]["values"][0][1] == pytest.approx(43.75 / 50)
    out = promql.evaluate(db, "increase(req_total[50s])", end, end, 15)
    assert out[0]["values"][0][1] == pytest.approx(43.75)
    out = promql.evaluate(db, "irate(req_total[50s])", end, end, 15)
    assert out[0]["values"][0][1] == pytest.approx(10 / 10)
    # two-series sum(rate) stays per-series then summed: series b window
    # holds 120..200 -> increase 80 * 50/40 = 100
    t.append_rows([{"time": base + i * 10, "metric_name": "req_total",
                    "labels_json": '{"job": "b"}', "value": float(100 + i * 20)}
                   for i in range(6)])
    out = promql.evaluate(db, "sum(rate(req_total[50s]))", end, end, 15)
    assert out[0]["values"][0][1] == pytest.approx(43.75 / 50 + 100 / 50)


def test_dfstats_rate_uses_counter_semantics():
    """deepflow_system values are cumulative process counters; rate() must
    diff them, not sum the snapshots."""
    db = Database()
    t = db.table("deepflow_system.deepflow_system")
    base = 2_000_000
    t.append_rows([
        {"time": (base + i * 10) * 1_000_000_000,
         "metric_name": "agent.sender",
         "value_name": "sent_frames", "tag_json": "{}", "host": "h1",
         "agent_id": 1, "value": float(1000 + i * 50)}
        for i in range(6)])
    end = base + 50
    out = promql.evaluate(
        db, "rate(deepflow_system_agent_sender_sent_frames[50s])",
        end, end, 15)
    # window (base, base+50]: 1050..1250 -> increase 200 over the 40s
    # sampled span, extrapolated to 250 over the 50s window
    assert out[0]["values"][0][1] == pytest.approx(250 / 50)


def test_counter_irate_duplicate_timestamps():
    """Remote-write retries duplicate rows at the same timestamp; irate must
    step back to the last two DISTINCT timestamps, not return nothing."""
    db = Database()
    t = db.table("prometheus.samples")
    base = 3_000_000
    rows = [{"time": base + i * 10, "metric_name": "dup_total",
             "labels_json": "{}", "value": float(100 + i * 10)}
            for i in range(4)]
    rows.append(dict(rows[-1]))  # duplicate of the last sample
    t.append_rows(rows)
    end = base + 30
    out = promql.evaluate(db, "irate(dup_total[40s])", end, end, 15)
    assert out and out[0]["values"][0][1] == pytest.approx(10 / 10)
