"""Tracing adapter: external APM spans spliced into the trace view.

Reference analog: server/querier/app/tracing-adapter (SkyWalking et al).
VERDICT round-1 §2.5 "Tracing adapter: no".
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from deepflow_tpu.query.tracing_adapter import (
    AdapterRegistry, JaegerAdapter, OtlpJsonAdapter)

TRACE_ID = "4bf92f3577b34da6a3ce929d0e0e4736"


class _FakeJaeger(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_GET(self):
        body = json.dumps({"data": [{
            "processes": {"p1": {"serviceName": "checkout"}},
            "spans": [
                {"spanID": "aaa1", "operationName": "charge-card",
                 "processID": "p1", "startTime": 1_000_100,
                 "duration": 400,
                 "references": [{"refType": "CHILD_OF",
                                 "spanID": "flowspan1"}]},
                {"spanID": "aaa2", "operationName": "emit-receipt",
                 "processID": "p1", "startTime": 1_000_600,
                 "duration": 100, "references": [
                     {"refType": "CHILD_OF", "spanID": "aaa1"}]},
            ]}]}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def test_jaeger_adapter_fetch():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeJaeger)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        spans = JaegerAdapter(
            f"http://127.0.0.1:{srv.server_port}").fetch(TRACE_ID)
        assert len(spans) == 2
        by_id = {s.span_id: s for s in spans}
        assert by_id["aaa1"].service == "checkout"
        assert by_id["aaa1"].parent_span_id == "flowspan1"
        assert by_id["aaa2"].parent_span_id == "aaa1"
        assert by_id["aaa1"].start_ns == 1_000_100_000
    finally:
        srv.shutdown()


def test_otlp_adapter_fetch():
    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = json.dumps({"resourceSpans": [{
                "resource": {"attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": "payments"}}]},
                "scopeSpans": [{"spans": [
                    {"spanId": "bbb1", "parentSpanId": "",
                     "name": "POST /pay",
                     "startTimeUnixNano": "1000",
                     "endTimeUnixNano": "2000"}]}]}]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        spans = OtlpJsonAdapter(
            f"http://127.0.0.1:{srv.server_port}").fetch(TRACE_ID)
        assert len(spans) == 1
        assert spans[0].service == "payments"
        assert spans[0].name == "POST /pay"
    finally:
        srv.shutdown()


def test_adapter_merges_into_flow_trace():
    """External spans splice under the flow span they reference; the trace
    endpoint serves the merged tree."""
    from deepflow_tpu.server import Server
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeJaeger)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        t = server.db.table("flow_log.l7_flow_log")
        t.append_rows([{
            "time": 1_000_000_000, "flow_id": 1,
            "request_type": "POST", "endpoint": "/checkout",
            "response_duration": 2_000_000,
            "trace_id": TRACE_ID, "span_id": "flowspan1",
            "response_status": 1, "response_code": 200,
        }])
        server.api.trace_adapters.add(
            "jaeger", f"http://127.0.0.1:{srv.server_port}")
        out = server.api.trace({"trace_id": TRACE_ID})["result"]
        assert out["span_count"] == 3
        assert out["external_spans"] == 2
        root = out["spans"][0]
        assert root["span_id"] == "flowspan1"
        child_names = {c["name"] for c in root["children"]}
        assert "charge-card" in child_names
        charge = [c for c in root["children"]
                  if c["name"] == "charge-card"][0]
        assert charge["children"][0]["name"] == "emit-receipt"
        assert charge["kind"] == "external"
    finally:
        server.stop()
        srv.shutdown()


def test_genesis_events_recorded():
    """Pod ADDED/DELETED from the watch land in event.event (recorder
    resource-diff analog)."""
    from deepflow_tpu.server.genesis import K8sGenesis
    from deepflow_tpu.server.platform_info import PodIpIndex
    rows = []
    gen = K8sGenesis(PodIpIndex(), api_base="http://127.0.0.1:1",
                     event_sink=lambda r: rows.extend(r))
    pod = {"metadata": {"name": "web-1", "namespace": "prod"},
           "spec": {"nodeName": "n1"},
           "status": {"podIP": "10.244.1.5",
                      "podIPs": [{"ip": "10.244.1.5"}]}}
    gen._apply("ADDED", pod)
    gen._apply("MODIFIED", pod)   # not an event
    gen._apply("DELETED", pod)
    assert [r["event_type"] for r in rows] == ["pod-added", "pod-deleted"]
    assert rows[0]["resource_name"] == "prod/web-1"
    assert "10.244.1.5" in rows[0]["description"]


def test_adapter_add_idempotent_and_remove():
    reg = AdapterRegistry()
    reg.add("jaeger", "http://x:1/")
    reg.add("jaeger", "http://x:1")     # dedup (trailing slash too)
    assert len(reg.list()) == 1
    assert reg.remove("http://x:1") is True
    assert reg.list() == []


def test_merge_survives_mutually_referencing_spans():
    """External spans forming a parent cycle fall back to containment —
    the merged tree must stay acyclic (json-serializable)."""
    from deepflow_tpu.query.tracing import TraceSpan
    reg = AdapterRegistry()

    class Fake:
        name = "fake"
        base = "x"

        def fetch(self, trace_id):
            return [
                TraceSpan(span_id="c1", parent_span_id="c2", name="a",
                          service="s", l7_protocol="app", start_ns=10,
                          end_ns=20, status="ok", response_code=0),
                TraceSpan(span_id="c2", parent_span_id="c1", name="b",
                          service="s", l7_protocol="app", start_ns=12,
                          end_ns=18, status="ok", response_code=0),
            ]

    reg._adapters.append(Fake())
    tree = {"trace_id": "t", "span_count": 1, "spans": [{
        "span_id": "flow1", "name": "root", "start_ns": 0, "end_ns": 100,
        "children": []}]}
    merged = reg.merge_into(tree, "t")
    json.dumps(merged)  # acyclic or this raises
    assert merged["external_spans"] == 2


def test_relist_does_not_reemit_added_events():
    """List seeding is silent, and a resync re-ADD of an IDENTICAL known
    object is a no-op in the diff engine; a real change emits a
    modified event with before/after attrs."""
    from deepflow_tpu.server.genesis import K8sGenesis
    from deepflow_tpu.server.platform_info import PodIpIndex
    rows = []
    gen = K8sGenesis(PodIpIndex(), api_base="http://127.0.0.1:1",
                     event_sink=lambda r: rows.extend(r))
    pod = {"metadata": {"name": "w", "namespace": "p"},
           "spec": {"nodeName": "n"},
           "status": {"podIP": "10.0.0.1", "podIPs": [{"ip": "10.0.0.1"}]}}
    gen._apply("ADDED", pod, emit_events=False)  # what list_once does
    assert rows == []
    gen._apply("ADDED", pod)        # resync of known identical state
    assert rows == []
    pod["spec"]["nodeName"] = "n2"  # rescheduled
    gen._apply("MODIFIED", pod)
    assert len(rows) == 1 and rows[0]["event_type"] == "pod-modified"
    import json as _json
    changed = _json.loads(rows[0]["attrs"])["changed"]
    assert changed["node"] == {"before": "n", "after": "n2"}


def test_adapter_rejects_empty_base_url():
    import pytest as _pytest
    reg = AdapterRegistry()
    with _pytest.raises(ValueError):
        reg.add("jaeger", "")
    with _pytest.raises(ValueError):
        reg.add("jaeger", "not-a-url")


def test_step_trace_empty_is_complete():
    from deepflow_tpu.tpuprobe.collectives import step_trace
    tr = step_trace([])
    assert tr["step_latency_ns"] == 0 and tr["device_skew_ns"] == 0
