import numpy as np

from deepflow_tpu.store import Database, Dictionary
from deepflow_tpu.store.table import ColumnSpec, ColumnarTable


def test_dictionary():
    d = Dictionary("t")
    assert d.encode("") == 0
    a = d.encode("alpha")
    b = d.encode("beta")
    assert d.encode("alpha") == a != b
    assert d.decode(b) == "beta"
    assert d.lookup("nope") is None
    ids = d.encode_batch(["alpha", "beta", "alpha"])
    assert isinstance(ids, np.ndarray) and ids.dtype == np.uint32
    assert ids.tolist() == [a, b, a]
    assert d.decode_many(ids) == ["alpha", "beta", "alpha"]
    m = d.match_ids(lambda s: s.startswith("a"))
    assert m.tolist() == [a]


def test_table_append_and_snapshot():
    t = ColumnarTable("t", [
        ColumnSpec("time", "u64"),
        ColumnSpec("name", "str"),
        ColumnSpec("kind", "enum", ("unknown", "tcp", "udp")),
        ColumnSpec("value", "f64"),
    ], chunk_rows=4)
    for i in range(10):  # one-by-one: chunks seal exactly at chunk_rows
        t.append_rows([{"time": i, "name": f"n{i % 3}",
                        "kind": 1 + (i % 2), "value": i * 1.5}])
    assert len(t) == 10
    chunks = t.snapshot()
    assert sum(len(c["time"]) for c in chunks) == 10
    # sealed chunks of 4 rows + tail buffer
    assert [len(c["time"]) for c in chunks] == [4, 4, 2]
    # dictionary encoding: only 3 unique names (+ empty)
    assert len(t.dicts["name"]) == 4
    cols = t.column_concat(["name", "value"])
    assert t.dicts["name"].decode(int(cols["name"][4])) == "n1"


def test_table_columns_append_defaults():
    t = ColumnarTable("t", [
        ColumnSpec("time", "u64"),
        ColumnSpec("svc", "str"),
        ColumnSpec("v", "u32", default=9),
    ])
    t.append_columns({"time": np.arange(3), "svc": ["a", "b", "a"]})
    cols = t.column_concat(["v", "svc"])
    assert cols["v"].tolist() == [9, 9, 9]


def test_trim_before():
    t = ColumnarTable("t", [ColumnSpec("time", "u64")], chunk_rows=10)
    for lo in range(0, 25, 10):
        t.append_rows([{"time": i} for i in range(lo, min(lo + 10, 25))])
    t.flush()
    dropped = t.trim_before("time", 10)
    assert dropped == 10
    assert len(t.snapshot()) == 2


def test_database_schema_tables():
    db = Database()
    assert "profile.in_process_profile" in db.tables()
    assert "profile.tpu_hlo_span" in db.tables()
    assert "flow_log.l7_flow_log" in db.tables()
    t = db.table("profile.in_process_profile")
    t.append_rows([{"time": 1, "stack": "a;b", "value": 5, "count": 1,
                    "event_type": 1, "app_service": "x"}])
    assert len(t) == 1


def test_save_load(tmp_path):
    t = ColumnarTable("t", [ColumnSpec("time", "u64"),
                            ColumnSpec("s", "str")])
    t.append_rows([{"time": 1, "s": "x"}, {"time": 2, "s": "y"}])
    t.save(str(tmp_path))
    t2 = ColumnarTable("t", [ColumnSpec("time", "u64"),
                             ColumnSpec("s", "str")])
    t2.load(str(tmp_path))
    assert len(t2) == 2
    cols = t2.column_concat(["s"])
    assert t2.dicts["s"].decode_many(cols["s"]) == ["x", "y"]


def test_append_columns_ragged_rejected():
    import pytest
    t = ColumnarTable("t", [ColumnSpec("a", "u32"), ColumnSpec("b", "u32")])
    with pytest.raises(ValueError):
        t.append_columns({"a": [1, 2, 3], "b": [10, 20]})
    assert len(t) == 0


def test_seal_poison_drops_window_not_table():
    import pytest
    t = ColumnarTable("t", [ColumnSpec("a", "u32")], chunk_rows=2)
    with pytest.raises(ValueError):
        t.append_rows([{"a": 1}, {"a": 10**18}])  # overflows u32 at seal
    # table still usable afterwards
    t.append_rows([{"a": 5}, {"a": 6}])
    t.flush()
    assert t.column_concat(["a"])["a"].tolist() == [5, 6]
    assert len(t) == 2


def test_trim_before_updates_len():
    """TTL trims must shrink __len__ (round-1 bug: rows_written never
    decremented, so stats and rollup early-outs overcounted forever)."""
    from deepflow_tpu.store.table import ColumnSpec, ColumnarTable

    t = ColumnarTable("trimtest", [
        ColumnSpec("time", "u64"),
        ColumnSpec("v", "f64"),
    ], chunk_rows=4)
    t.append_columns({"time": [1, 2, 3, 4], "v": [0.0] * 4})   # sealed
    t.append_columns({"time": [10, 11, 12, 13], "v": [0.0] * 4})  # sealed
    assert len(t) == 8
    dropped = t.trim_before("time", 5)
    assert dropped == 4
    assert len(t) == 4
