import pytest

from deepflow_tpu.codec import (
    FrameDecodeError, FrameHeader, MessageType, StreamDecoder,
    decode_frame, encode_frame)
from deepflow_tpu.proto import pb


def test_roundtrip_small():
    h = FrameHeader(MessageType.PROFILE, agent_id=7)
    frame = encode_frame(h, b"hello")
    h2, payload, consumed = decode_frame(frame)
    assert consumed == len(frame)
    assert payload == b"hello"
    assert h2.msg_type == MessageType.PROFILE
    assert h2.agent_id == 7
    assert not h2.compressed


def test_roundtrip_compressed():
    data = b"x" * 10000
    frame = encode_frame(FrameHeader(MessageType.METRICS), data)
    h2, payload, _ = decode_frame(frame)
    assert h2.compressed
    assert payload == data
    assert len(frame) < len(data)


def test_partial_and_stream():
    frames = [encode_frame(FrameHeader(MessageType.L4_LOG, agent_id=i),
                           bytes([i]) * (10 + i)) for i in range(5)]
    blob = b"".join(frames)
    dec = StreamDecoder()
    got = []
    # feed in awkward 7-byte chunks
    for i in range(0, len(blob), 7):
        got.extend(dec.feed(blob[i:i + 7]))
    assert len(got) == 5
    for i, (h, p) in enumerate(got):
        assert h.agent_id == i
        assert p == bytes([i]) * (10 + i)


def test_corruption_detected():
    frame = bytearray(encode_frame(FrameHeader(MessageType.PROFILE), b"data!"))
    frame[-1] ^= 0xFF
    with pytest.raises(FrameDecodeError):
        decode_frame(bytes(frame))
    frame2 = bytearray(encode_frame(FrameHeader(MessageType.PROFILE), b"y"))
    frame2[4] = 0  # magic
    with pytest.raises(FrameDecodeError):
        decode_frame(bytes(frame2))


def test_protobuf_payload():
    batch = pb.ProfileBatch()
    p = batch.profiles.add()
    p.process_name = "querier"
    p.event_type = pb.ON_CPU
    p.stack = b"main;run;loop"
    p.value = 10000
    p.count = 1
    frame = encode_frame(FrameHeader(MessageType.PROFILE),
                         batch.SerializeToString())
    _, payload, _ = decode_frame(frame)
    out = pb.ProfileBatch.FromString(payload)
    assert out.profiles[0].stack == b"main;run;loop"


def test_stream_decoder_recovers_after_corruption():
    good = encode_frame(FrameHeader(MessageType.PROFILE), b"ok")
    bad = bytearray(good)
    bad[-1] ^= 0xFF
    dec = StreamDecoder()
    with pytest.raises(FrameDecodeError):
        dec.feed(bytes(bad))
    # buffer discarded: a fresh good frame decodes fine
    assert dec.feed(good)[0][1] == b"ok"


def test_unknown_msg_type_is_decode_error():
    frame = bytearray(encode_frame(FrameHeader(MessageType.PROFILE), b"x"))
    frame[7] = 200  # msg_type byte
    # crc covers payload only, so this is a header corruption case
    with pytest.raises(FrameDecodeError):
        decode_frame(bytes(frame))
