"""SQL engine depth: HAVING, derived metrics, SHOW introspection.

Golden-style tests in the spirit of the reference's
server/querier/engine/clickhouse/clickhouse_test.go table of
(sql, expected) pairs.
"""

import pytest

from deepflow_tpu.query import catalog
from deepflow_tpu.query import sql as S
from deepflow_tpu.query.engine import QueryError, execute
from deepflow_tpu.store import Database


def _network_1m():
    db = Database()
    t = db.table("flow_metrics.network.1m")
    t.append_rows([
        # pod web-1: two rows, avg rtt = (300+100)/(2+2) = 100 us
        {"time": 60, "pod_0": "web-1", "service_1": "db-svc",
         "rtt_sum": 300, "rtt_count": 2, "byte_tx": 10},
        {"time": 120, "pod_0": "web-1", "service_1": "db-svc",
         "rtt_sum": 100, "rtt_count": 2, "byte_tx": 30},
        # pod web-2: avg rtt = 9000/1 = 9000 us
        {"time": 60, "pod_0": "web-2", "service_1": "db-svc",
         "rtt_sum": 9000, "rtt_count": 1, "byte_tx": 100},
        # different service, high rtt but filtered by WHERE
        {"time": 60, "pod_0": "web-3", "service_1": "other",
         "rtt_sum": 5000, "rtt_count": 1, "byte_tx": 5},
    ])
    return db, t


def test_having_filters_groups():
    db, t = _network_1m()
    res = execute(t, "SELECT pod_0, Sum(byte_tx) AS b FROM t "
                     "GROUP BY pod_0 HAVING Sum(byte_tx) > 20 "
                     "ORDER BY b DESC")
    assert res.values == [["web-2", 100.0], ["web-1", 40.0]]


def test_having_with_string_group_key():
    db, t = _network_1m()
    res = execute(t, "SELECT service_1, Sum(byte_tx) FROM t "
                     "GROUP BY service_1 HAVING service_1 = 'db-svc'")
    assert res.values == [["db-svc", 140.0]]


def test_reference_style_flagship_query():
    """The VERDICT's acid test: SELECT pod, Avg(rtt) ... WHERE service
    ... GROUP BY pod HAVING Avg(rtt) > threshold."""
    db, t = _network_1m()
    res = execute(t, "SELECT pod_0, Avg(rtt) AS art FROM t "
                     "WHERE service_1 = 'db-svc' "
                     "GROUP BY pod_0 HAVING Avg(rtt) > 1000")
    assert res.values == [["web-2", 9000.0]]
    # and the complement
    res = execute(t, "SELECT pod_0, Avg(rtt) AS art FROM t "
                     "WHERE service_1 = 'db-svc' "
                     "GROUP BY pod_0 HAVING Avg(rtt) <= 1000")
    assert res.values == [["web-1", 100.0]]


def test_derived_avg_rtt_is_sum_ratio_not_avg_of_avgs():
    db, t = _network_1m()
    res = execute(t, "SELECT Avg(rtt) FROM t WHERE pod_0 = 'web-1'")
    # (300+100)/(2+2) = 100, NOT avg(150, 50) = 100 here but the ratio
    # semantics matter with uneven counts:
    assert res.values == [[100.0]]
    t.append_rows([{"time": 180, "pod_0": "web-1", "service_1": "db-svc",
                    "rtt_sum": 400, "rtt_count": 8, "byte_tx": 0}])
    res = execute(t, "SELECT Avg(rtt) FROM t WHERE pod_0 = 'web-1'")
    # (300+100+400)/(2+2+8) = 800/12, not mean(150,50,50)
    assert res.values[0][0] == pytest.approx(800 / 12)


def test_derived_rrt_max_and_error_sum():
    db = Database()
    t = db.table("flow_metrics.application.1m")
    t.append_rows([
        {"time": 60, "app_service": "a", "rrt_sum": 100, "rrt_count": 1,
         "rrt_max": 70, "error_client": 2, "error_server": 1},
        {"time": 120, "app_service": "a", "rrt_sum": 300, "rrt_count": 3,
         "rrt_max": 250, "error_client": 0, "error_server": 4},
    ])
    res = execute(t, "SELECT Max(rrt), Avg(rrt), Sum(error) FROM t")
    assert res.values == [[250.0, 100.0, 7.0]]


def test_derived_unsupported_aggregate_is_clear_error():
    db, t = _network_1m()
    with pytest.raises(QueryError, match="not defined for derived"):
        execute(t, "SELECT Percentile(rtt, 95) FROM t")


def test_raw_table_rtt_column_not_rewritten():
    """flow_log.l4_flow_log has a REAL rtt column; the derived registry
    must not shadow it."""
    db = Database()
    t = db.table("flow_log.l4_flow_log")
    t.append_rows([{"time": 1, "rtt": 500}, {"time": 2, "rtt": 700}])
    res = execute(t, "SELECT Avg(rtt) FROM t")
    assert res.values == [[600.0]]


def test_having_without_group_by():
    db, t = _network_1m()
    # single implicit group; HAVING filters it in or out wholesale
    res = execute(t, "SELECT Sum(byte_tx) FROM t HAVING Sum(byte_tx) > 1000")
    assert res.values == []
    res = execute(t, "SELECT Sum(byte_tx) FROM t HAVING Sum(byte_tx) > 10")
    assert res.values == [[145.0]]


# -- SHOW introspection ----------------------------------------------------


def test_show_databases_and_tables():
    res = catalog.show("databases")
    dbs = [r[0] for r in res["values"]]
    assert {"flow_log", "flow_metrics", "profile", "event",
            "prometheus"} <= set(dbs)
    res = catalog.show("tables")
    tables = [r[0] for r in res["values"]]
    assert "flow_log.l7_flow_log" in tables
    assert "flow_metrics.network.1m" in tables


def test_show_tags_classifies_dimensions():
    res = catalog.show("tags", "flow_log.l7_flow_log")
    names = {r[0] for r in res["values"]}
    # strings, enums, universal + per-side tags are tags
    assert {"l7_protocol", "request_resource", "trace_id", "pod_0",
            "service_1", "az_0", "host", "agent_id"} <= names
    # metrics are NOT tags
    assert "response_duration" not in names
    # enum tags carry their value set for autocomplete
    enum_row = next(r for r in res["values"] if r[0] == "response_status")
    assert enum_row[1] == "enum" and "server_error" in enum_row[2]


def test_show_metrics_includes_derived():
    res = catalog.show("metrics", "flow_metrics.network.1m")
    names = {r[0] for r in res["values"]}
    assert {"byte_tx", "rtt_sum", "rtt_count", "rtt"} <= names
    derived_row = next(r for r in res["values"] if r[0] == "rtt")
    assert "derived" in derived_row[1]
    # tags are NOT metrics
    assert "pod_0" not in names and "server_port" not in names


def test_show_resolves_short_names():
    # e.g. `show tags from network` hits flow_metrics.network.1s
    res = catalog.show("tags", "network")
    assert res["table"] == "flow_metrics.network.1s"


def test_show_statement_parses():
    stmt = S.parse_statement("SHOW TAGS FROM flow_log.l4_flow_log")
    assert isinstance(stmt, S.Show)
    assert stmt.what == "tags" and stmt.table == "flow_log.l4_flow_log"
    stmt = S.parse_statement("show databases")
    assert stmt.what == "databases"
    sel = S.parse_statement("SELECT 1 FROM t")
    assert isinstance(sel, S.Select)
    with pytest.raises(S.SqlError):
        S.parse_statement("SHOW nonsense")
    with pytest.raises(S.SqlError):
        S.parse_statement("SHOW TAGS")  # missing FROM


def test_show_over_http_api():
    import json
    import urllib.request

    from deepflow_tpu.server import Server
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{s.query_port}/v1/query/",
            data=json.dumps({"sql": "show tags from "
                                    "flow_metrics.application.1m"}).encode(),
            headers={"Content-Type": "application/json"}), timeout=5)
        out = json.loads(r.read())
        names = {row[0] for row in out["result"]["values"]}
        assert "app_service" in names and "service_0" in names
    finally:
        s.stop()


def test_order_by_derived_metric():
    db, t = _network_1m()
    res = execute(t, "SELECT pod_0, Avg(rtt) FROM t GROUP BY pod_0 "
                     "ORDER BY Avg(rtt) DESC LIMIT 1")
    assert res.columns == ["pod_0", "AVG(rtt)"]
    assert res.values == [["web-2", 9000.0]]


def test_having_enum_in():
    db = Database()
    t = db.table("flow_log.l4_flow_log")
    t.append_rows([{"time": 1, "protocol": 1, "byte_tx": 10},
                   {"time": 2, "protocol": 2, "byte_tx": 20}])
    res = execute(t, "SELECT protocol, Sum(byte_tx) FROM t "
                     "GROUP BY protocol HAVING protocol IN ('tcp')")
    assert res.values == [["tcp", 10.0]]


def test_derived_column_display_name():
    db, t = _network_1m()
    res = execute(t, "SELECT Avg(rtt) FROM t")
    assert res.columns == ["AVG(rtt)"]


# -- round 5: CASE WHEN, COUNT(DISTINCT), GROUP BY alias ---------------------

def _lat_table():
    from deepflow_tpu.store.table import ColumnarTable, ColumnSpec as C
    t = ColumnarTable("t", [C("time", "u64"), C("svc", "str"),
                            C("lat", "u32")])
    t.append_rows([{"time": i * 10**9, "svc": f"s{i % 3}", "lat": i * 10}
                   for i in range(100)])
    return t


def test_count_distinct():
    t = _lat_table()
    r = execute(t, "SELECT Count(DISTINCT svc) FROM t")
    assert r.values == [[3.0]]
    r = execute(t, "SELECT svc, Count(DISTINCT lat) AS n FROM t "
                   "GROUP BY svc ORDER BY svc")
    assert [row[1] for row in r.values] == [34.0, 33.0, 33.0]
    r = execute(t, "SELECT svc FROM t GROUP BY svc "
                   "HAVING Count(DISTINCT lat) > 33")
    assert r.values == [["s0"]]


def test_case_when_row_level():
    t = _lat_table()
    r = execute(t, "SELECT CASE WHEN lat > 900 THEN 'vslow' "
                   "WHEN lat > 500 THEN 'slow' ELSE 'fast' END AS c, "
                   "Count(), Avg(lat) FROM t GROUP BY c ORDER BY c")
    assert [row[0] for row in r.values] == ["fast", "slow", "vslow"]
    assert [row[1] for row in r.values] == [51.0, 40.0, 9.0]
    # numeric branches stay numeric
    r = execute(t, "SELECT CASE WHEN lat > 500 THEN 1 ELSE 0 END AS hot, "
                   "Count() FROM t GROUP BY hot ORDER BY hot")
    assert r.values == [[0.0, 51.0], [1.0, 49.0]]
    # no ELSE: unmatched numeric rows are NaN-excluded from labels path
    r = execute(t, "SELECT CASE WHEN lat > 500 THEN 'slow' END AS c, "
                   "Count() FROM t GROUP BY c ORDER BY c")
    assert {row[0] for row in r.values} == {"", "slow"}


def test_case_over_aggregates():
    t = _lat_table()
    r = execute(t, "SELECT svc, CASE WHEN Avg(lat) > 490 THEN 'hot' "
                   "ELSE 'cold' END AS heat FROM t GROUP BY svc "
                   "ORDER BY svc")
    assert r.values == [["s0", "hot"], ["s1", "cold"], ["s2", "hot"]]


def test_group_by_alias():
    t = _lat_table()
    r = execute(t, "SELECT Time(time, 10) AS bucket, Count() FROM t "
                   "GROUP BY bucket ORDER BY bucket")
    assert len(r.values) == 10 and r.values[0][1] == 10.0
    # an alias shadowing a REAL column still groups by the column
    r = execute(t, "SELECT svc AS lat, Count() FROM t GROUP BY lat")
    assert len(r.values) == 100  # grouped by the real lat column
