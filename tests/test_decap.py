"""Tunnel decapsulation: VXLAN / GENEVE / GRE(TEB) / ERSPAN.

Reference analog: agent/src/common/decapsulate.rs (the reference strips
tunnel layers before flow lookup so mirrored/overlay traffic is attributed
to the inner endpoints). Both decode engines are covered: the native C++
fast path and the pure-Python fallback.
"""

import struct

import pytest

from deepflow_tpu import native
from deepflow_tpu.agent.packet import decode_ethernet


def eth(etype: int, payload: bytes) -> bytes:
    return b"\xaa" * 6 + b"\xbb" * 6 + struct.pack(">H", etype) + payload


def ipv4(proto: int, src: bytes, dst: bytes, payload: bytes) -> bytes:
    return struct.pack(">BBHHHBBH4s4s", 0x45, 0, 20 + len(payload), 0, 0,
                       64, proto, 0, src, dst) + payload


def tcp(sp: int, dp: int, payload: bytes = b"") -> bytes:
    return struct.pack(">HHIIBBHHH", sp, dp, 100, 200, 5 << 4, 0x18,
                       1024, 0, 0) + payload


def udp(sp: int, dp: int, payload: bytes) -> bytes:
    return struct.pack(">HHHH", sp, dp, 8 + len(payload), 0) + payload


INNER = eth(0x0800, ipv4(6, bytes([10, 1, 0, 1]), bytes([10, 1, 0, 2]),
                         tcp(40000, 443, b"inner-payload")))


def vxlan_frame(vni: int = 77) -> bytes:
    hdr = struct.pack(">BBHI", 0x08, 0, 0, vni << 8)
    return eth(0x0800, ipv4(17, bytes([172, 16, 0, 1]),
                            bytes([172, 16, 0, 2]),
                            udp(49152, 4789, hdr + INNER)))


def geneve_frame(vni: int = 88, n_opts_words: int = 1) -> bytes:
    opts = b"\x00" * (n_opts_words * 4)
    # VNI occupies bytes 4-6 of the header, then a reserved byte
    hdr = (struct.pack(">BBH", n_opts_words, 0, 0x6558)
           + bytes([(vni >> 16) & 255, (vni >> 8) & 255, vni & 255, 0])
           + opts)
    return eth(0x0800, ipv4(17, bytes([172, 16, 0, 1]),
                            bytes([172, 16, 0, 2]),
                            udp(49152, 6081, hdr + INNER)))


def gre_teb_frame(key: int | None = 123) -> bytes:
    flags = 0x2000 if key is not None else 0
    gre = struct.pack(">HH", flags, 0x6558)
    if key is not None:
        gre += struct.pack(">I", key)
    return eth(0x0800, ipv4(47, bytes([172, 16, 0, 1]),
                            bytes([172, 16, 0, 2]), gre + INNER))


def erspan2_frame(session: int = 5) -> bytes:
    gre = struct.pack(">HH", 0x1000, 0x88BE) + struct.pack(">I", 9)  # seq
    ers = struct.pack(">HHI", 0x1000, session & 0x3FF, 0)
    return eth(0x0800, ipv4(47, bytes([172, 16, 0, 1]),
                            bytes([172, 16, 0, 2]), gre + ers + INNER))


def erspan1_frame() -> bytes:
    gre = struct.pack(">HH", 0, 0x88BE)  # no seq bit: type I, no header
    return eth(0x0800, ipv4(47, bytes([172, 16, 0, 1]),
                            bytes([172, 16, 0, 2]), gre + INNER))


CASES = [
    ("vxlan", vxlan_frame(), 1, 77),
    ("geneve", geneve_frame(), 2, 88),
    ("gre-teb", gre_teb_frame(), 4, 123),
    ("erspan2", erspan2_frame(), 3, 5),
    ("erspan1", erspan1_frame(), 3, 0),
]


@pytest.mark.parametrize("name,frame,ttype,tid", CASES)
def test_python_decap(name, frame, ttype, tid):
    mp = decode_ethernet(frame, 1)
    assert mp is not None, name
    assert mp.protocol == 1
    assert mp.ip_src == bytes([10, 1, 0, 1])
    assert mp.ip_dst == bytes([10, 1, 0, 2])
    assert (mp.port_src, mp.port_dst) == (40000, 443)
    assert mp.payload == b"inner-payload"
    assert mp.tunnel_type == ttype, name
    assert mp.tunnel_id == tid, name


@pytest.mark.parametrize("name,frame,ttype,tid", CASES)
def test_native_decap(name, frame, ttype, tid):
    if native.load() is None:
        pytest.skip("libdfnative.so unavailable")
    out, ok = native.decode_eth_batch([frame])
    assert ok[0], name
    r = out[0]
    assert r["protocol"] == 1
    assert r["ip_src"] == 0x0A010001 and r["ip_dst"] == 0x0A010002
    assert (r["port_src"], r["port_dst"]) == (40000, 443)
    assert frame[r["payload_off"]:r["payload_off"] + r["payload_len"]] \
        == b"inner-payload"
    assert r["tunnel_type"] == ttype, name
    assert r["tunnel_id"] == tid, name


def ipv6(next_header: int, src: bytes, dst: bytes, payload: bytes) -> bytes:
    return struct.pack(">IHBB16s16s", 6 << 28, len(payload), next_header,
                       64, src, dst) + payload


def test_inner_ipv6_defers_to_python_and_decaps():
    """VXLAN with an IPv6 inner frame: the native fast path must NOT
    report the outer VTEP UDP flow (merging all tenants) — it defers to
    the Python slow path, which decapsulates the v6 inner."""
    inner6 = eth(0x86DD, ipv6(6, b"\x20\x01" + b"\x00" * 13 + b"\x01",
                              b"\x20\x01" + b"\x00" * 13 + b"\x02",
                              tcp(50000, 443, b"v6-inner")))
    hdr = struct.pack(">BBHI", 0x08, 0, 0, 66 << 8)
    frame = eth(0x0800, ipv4(17, bytes([172, 16, 0, 1]),
                             bytes([172, 16, 0, 2]),
                             udp(49152, 4789, hdr + inner6)))
    if native.load() is not None:
        out, ok = native.decode_eth_batch([frame])
        assert not ok[0], "native must defer inner-v6 tunnels"
    mp = decode_ethernet(frame, 1)
    assert mp is not None and mp.protocol == 1
    assert mp.tunnel_type == 1 and mp.tunnel_id == 66
    assert mp.port_dst == 443 and len(mp.ip_dst) == 16


def test_decap_packet_len_is_outer_wire_length():
    """Byte metrics count wire bytes: the Python decap path must report
    the OUTER frame length, matching the native path."""
    frame = vxlan_frame()
    mp = decode_ethernet(frame, 1)
    assert mp.packet_len == len(frame)


def test_non_tunnel_udp_unchanged():
    plain = eth(0x0800, ipv4(17, bytes([10, 0, 0, 1]), bytes([10, 0, 0, 2]),
                             udp(1111, 2222, b"dns-ish")))
    mp = decode_ethernet(plain, 1)
    assert mp.protocol == 2 and mp.tunnel_type == 0
    assert mp.payload == b"dns-ish"
    if native.load() is not None:
        out, ok = native.decode_eth_batch([plain])
        assert ok[0] and out[0]["tunnel_type"] == 0
        assert out[0]["protocol"] == 2


def test_vxlan_port_without_iflag_stays_udp():
    # dst 4789 but the I-flag is clear: NOT vxlan, keep the outer UDP
    bad = struct.pack(">BBHI", 0x00, 0, 0, 1 << 8) + INNER
    frame = eth(0x0800, ipv4(17, bytes([1, 1, 1, 1]), bytes([2, 2, 2, 2]),
                             udp(5, 4789, bad)))
    mp = decode_ethernet(frame, 1)
    assert mp.protocol == 2 and mp.tunnel_type == 0
    assert mp.port_dst == 4789


def test_truncated_tunnel_is_safe():
    for frame in (vxlan_frame()[:60], gre_teb_frame()[:40],
                  erspan2_frame()[:45]):
        decode_ethernet(frame, 1)  # must not raise
        if native.load() is not None:
            native.decode_eth_batch([frame])  # must not crash


def _vxlan_syn_frames(vni: int):
    frames = []
    for flags, seq in ((0x02, 1), (0x12, 1), (0x10, 2)):
        t = struct.pack(">HHIIBBHHH", 40000, 443, seq, 2, 5 << 4, flags,
                        1024, 0, 0)
        inner = eth(0x0800, ipv4(6, bytes([10, 1, 0, 1]),
                                 bytes([10, 1, 0, 2]), t))
        hdr = struct.pack(">BBHI", 0x08, 0, 0, vni << 8)
        frames.append(eth(0x0800, ipv4(
            17, bytes([172, 16, 0, 1]), bytes([172, 16, 0, 2]),
            udp(49152, 4789, hdr + inner))))
    return frames


def test_overlapping_tenant_space_stays_separate_flows():
    """Two VNIs carrying IDENTICAL inner 5-tuples must NOT merge into one
    flow — both engines."""
    # python engine
    from deepflow_tpu.agent.flow_map import FlowMap
    fm = FlowMap()
    for vni in (10, 20):
        for f in _vxlan_syn_frames(vni):
            mp = decode_ethernet(f, 1_000_000_000)
            fm.inject(mp)
    assert len(fm.flows) == 2
    tunnels = sorted(n.tunnel_id for n in fm.flows.values())
    assert tunnels == [10, 20]
    # native engine
    if native.load() is None:
        return
    import numpy as np

    from deepflow_tpu.agent.native_flow import NativeFlowMap
    l4s = []
    nfm = NativeFlowMap(on_l4_log=l4s.append)
    frames = _vxlan_syn_frames(10) + _vxlan_syn_frames(20)
    offsets = np.zeros(len(frames) + 1, dtype=np.uint32)
    total = 0
    for i, f in enumerate(frames):
        total += len(f)
        offsets[i + 1] = total
    ts = np.arange(1_000_000_000, 1_000_000_000 + len(frames),
                   dtype=np.uint64)
    nfm.inject_batch(b"".join(frames), offsets, ts)
    nfm.flush_all()
    assert len(l4s) == 2, [(x.ip_src_str(), x.tunnel_id) for x in l4s]
    assert sorted(x.tunnel_id for x in l4s) == [10, 20]
    assert all(x.tunnel_type == 1 for x in l4s)


def test_native_pcap_materialization_keeps_tunnel_fields():
    """read_pcap parity: the native batch path must stamp tunnel fields
    like the Python fallback does."""
    if native.load() is None:
        pytest.skip("libdfnative.so unavailable")
    import struct as _s
    import tempfile

    frame = vxlan_frame(55)
    with tempfile.NamedTemporaryFile(suffix=".pcap", delete=False) as f:
        f.write(_s.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 65535, 1))
        f.write(_s.pack("<IIII", 1, 0, len(frame), len(frame)))
        f.write(frame)
        path = f.name
    from deepflow_tpu.agent.packet import read_pcap
    for use_native in (True, False):
        pkts = read_pcap(path, use_native=use_native)
        assert len(pkts) == 1
        assert pkts[0].tunnel_type == 1, use_native
        assert pkts[0].tunnel_id == 55, use_native


def test_mirror_mode_requires_interface():
    from deepflow_tpu.agent.config import AgentConfig
    cfg = AgentConfig()
    cfg.flow.capture_mode = "mirror"
    cfg.flow.interface = ""
    with pytest.raises(ValueError):
        cfg.validate()
    cfg.flow.interface = "eth0"
    cfg.validate()


def test_native_flow_map_keys_on_inner_tuple():
    """Flows from mirrored VXLAN traffic attribute to the inner endpoints
    (the whole point of decap)."""
    if native.load() is None:
        pytest.skip("libdfnative.so unavailable")
    import numpy as np

    from deepflow_tpu.agent.native_flow import NativeFlowMap
    l4s = []
    nfm = NativeFlowMap(on_l4_log=l4s.append)
    frames = []
    for flags, seq in ((0x02, 1), (0x12, 1), (0x10, 2)):  # handshake
        t = struct.pack(">HHIIBBHHH", 40000, 443, seq, 2, 5 << 4, flags,
                        1024, 0, 0)
        inner = eth(0x0800, ipv4(6, bytes([10, 1, 0, 1]),
                                 bytes([10, 1, 0, 2]), t))
        hdr = struct.pack(">BBHI", 0x08, 0, 0, 77 << 8)
        frames.append(eth(0x0800, ipv4(
            17, bytes([172, 16, 0, 1]), bytes([172, 16, 0, 2]),
            udp(49152, 4789, hdr + inner))))
    offsets = np.zeros(len(frames) + 1, dtype=np.uint32)
    total = 0
    for i, f in enumerate(frames):
        total += len(f)
        offsets[i + 1] = total
    ts = np.arange(1_000_000_000, 1_000_000_000 + len(frames),
                   dtype=np.uint64)
    nfm.inject_batch(b"".join(frames), offsets, ts)
    nfm.flush_all()
    assert l4s, "no flow produced"
    f = l4s[0]
    assert f.ip_src_str() == "10.1.0.1"
    assert f.ip_dst_str() == "10.1.0.2"
    assert f.port_dst == 443


def _vxlan_http_frames(vni: int = 33) -> list[bytes]:
    """An HTTP request + response riding a VXLAN overlay."""
    req = b"GET /health HTTP/1.1\r\nHost: a\r\n\r\n"
    resp = b"HTTP/1.1 200 OK\r\nContent-Length: 0\r\n\r\n"

    def mk(src, dst, sp, dp, payload, seq):
        t = struct.pack(">HHIIBBHHH", sp, dp, seq, 1, 5 << 4, 0x18,
                        1024, 0, 0) + payload
        inner = eth(0x0800, ipv4(6, src, dst, t))
        hdr = struct.pack(">BBHI", 0x08, 0, 0, vni << 8)
        return eth(0x0800, ipv4(17, bytes([172, 16, 0, 1]),
                                bytes([172, 16, 0, 2]),
                                udp(49152, 4789, hdr + inner)))

    a, b = bytes([10, 1, 0, 1]), bytes([10, 1, 0, 2])
    return [mk(a, b, 40000, 80, req, 1), mk(b, a, 80, 40000, resp, 1)]


def test_l7_log_carries_tunnel_identity():
    """L7 records from overlay traffic must keep the VNI: without it two
    tenants with overlapping pod IPs produce byte-identical L7 logs."""
    from deepflow_tpu.agent.dispatcher import record_to_l7_pb
    from deepflow_tpu.agent.flow_map import FlowMap

    # python engine
    recs = []
    fm = FlowMap(on_l7_log=recs.append)
    for i, f in enumerate(_vxlan_http_frames()):
        fm.inject(decode_ethernet(f, 1_000_000_000 + i * 1_000_000))
    assert recs, "no L7 record from python engine"
    f = record_to_l7_pb(recs[0])
    assert f.key.tunnel_type == 1 and f.key.tunnel_id == 33
    assert f.request_resource == "/health"

    # native engine
    if native.load() is None:
        pytest.skip("libdfnative.so unavailable")
    from deepflow_tpu.agent.native_flow import NativeFlowMap
    recs2 = []
    nfm = NativeFlowMap(on_l7_log=recs2.append)
    nfm.inject_frames([(fr, 1_000_000_000 + i)
                       for i, fr in enumerate(_vxlan_http_frames())])
    nfm.flush_all()
    assert recs2, "no L7 record from native engine"
    f2 = record_to_l7_pb(recs2[0])
    assert f2.key.tunnel_type == 1 and f2.key.tunnel_id == 33
    assert f2.request_resource == "/health"


def test_analyzer_mode_no_exclusions_and_validation():
    """Analyzer mode (reference: dispatcher analyzer mode): dedicated
    analyzer NIC — promiscuous, NO self-port exclusions (the monitored
    fleet's telemetry ports must stay visible); config requires an
    interface."""
    import pytest
    from deepflow_tpu.agent.config import AgentConfig
    from deepflow_tpu.agent.live_capture import LiveCapture

    lc = LiveCapture(dispatcher=None, interface="mon0",
                     exclude_ports=(20033, 20035), capture_mode="analyzer")
    assert lc.exclude_ports == frozenset()
    lc_mirror = LiveCapture(dispatcher=None, interface="mon0",
                            exclude_ports=(20033,), capture_mode="mirror")
    assert 20033 in lc_mirror.exclude_ports  # mirror keeps exclusions

    cfg = AgentConfig()
    cfg.flow.enabled = True
    cfg.flow.capture_mode = "analyzer"
    cfg.flow.interface = ""
    with pytest.raises(ValueError, match="analyzer"):
        cfg.validate()
    cfg.flow.interface = "mon0"
    cfg.validate()
    cfg.flow.capture_mode = "bogus"
    with pytest.raises(ValueError, match="local|mirror|analyzer"):
        cfg.validate()
