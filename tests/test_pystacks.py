"""Remote CPython stack reading (pystacks) proven end-to-end: a child
python process with a known call chain must show its qualnames — directly
via RemotePython.sample(), and spliced over the libpython interpreter run
in the extprofiler's folded output (VERDICT r04 weak #2).

Reference analog: EE interpreter unwinding hooked from
agent/src/ebpf/kernel/perf_profiler.bpf.c:1015; ours is process_vm_readv
based (agent/pystacks.py).
"""

import ctypes
import os
import subprocess
import sys
import textwrap
import time

import pytest

CHILD_CODE = textwrap.dedent("""
    import sys

    def deep_leaf_spin():
        i = 0
        while True:
            i += 1

    def middle_hop():
        deep_leaf_spin()

    def outer_entry():
        middle_hop()

    sys.stdout.write("ready\\n")
    sys.stdout.flush()
    outer_entry()
""")


def _spawn_child():
    proc = subprocess.Popen([sys.executable, "-c", CHILD_CODE],
                            stdout=subprocess.PIPE)
    assert proc.stdout.readline().strip() == b"ready"
    time.sleep(0.1)
    return proc


def _calibrated() -> bool:
    from deepflow_tpu.agent import pystacks
    return pystacks.offsets() is not None


if not _calibrated():
    pytest.skip("pystacks calibration unavailable on this interpreter",
                allow_module_level=True)


def test_remote_sample_known_call_chain():
    """RemotePython.sample() on a same-build child returns the child's
    qualnames root-first."""
    from deepflow_tpu.agent.pystacks import RemotePython
    proc = _spawn_child()
    try:
        rp = RemotePython(proc.pid)
        found = None
        for _ in range(20):  # the leaf spin is steady; retry torn reads
            stacks = rp.sample()
            for frames in stacks.values():
                if any("deep_leaf_spin" in f for f in frames):
                    found = frames
                    break
            if found:
                break
            time.sleep(0.05)
    finally:
        proc.kill()
    assert found, "child call chain never observed"
    names = [f.split(":", 1)[-1] for f in found]
    assert "outer_entry" in names and "middle_hop" in names \
        and "deep_leaf_spin" in names, found
    # root-first ordering
    assert names.index("outer_entry") < names.index("middle_hop") \
        < names.index("deep_leaf_spin"), found


def test_remote_sample_sees_threads():
    """Each python thread appears under its native tid."""
    from deepflow_tpu.agent.pystacks import RemotePython
    code = textwrap.dedent("""
        import sys, threading

        def worker_spin_fn():
            i = 0
            while True:
                i += 1

        ts = [threading.Thread(target=worker_spin_fn, daemon=True)
              for _ in range(2)]
        [t.start() for t in ts]
        sys.stdout.write("ready\\n")
        sys.stdout.flush()
        import time
        while True:
            time.sleep(1)
    """)
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE)
    try:
        assert proc.stdout.readline().strip() == b"ready"
        time.sleep(0.1)
        rp = RemotePython(proc.pid)
        best: dict = {}
        for _ in range(20):
            stacks = rp.sample()
            if len(stacks) > len(best):
                best = stacks
            hits = sum(1 for fr in best.values()
                       if any("worker_spin_fn" in f for f in fr))
            if hits >= 2 and len(best) >= 3:
                break
            time.sleep(0.05)
    finally:
        proc.kill()
    hits = sum(1 for fr in best.values()
               if any("worker_spin_fn" in f for f in fr))
    assert hits >= 2, best
    # the blocked main thread must be visible too (list tail via `next`)
    assert len(best) >= 3, best


def test_non_python_target_fails_closed():
    """A non-Python pid must raise (no image with _PyRuntime) — never
    splice garbage."""
    from deepflow_tpu.agent.pystacks import RemotePython
    proc = subprocess.Popen(["/bin/sleep", "30"])
    try:
        time.sleep(0.1)
        with pytest.raises(RuntimeError):
            RemotePython(proc.pid)
    finally:
        proc.kill()


def test_build_identity_guard(monkeypatch):
    """If the target's python image is a DIFFERENT file than ours (maps
    dev:inode differ — e.g. a containerized target whose path string
    matches a host file), attach must refuse even though the image
    defines _PyRuntime (ADVICE r04 medium: calibrated offsets must not
    transfer across builds)."""
    from deepflow_tpu.agent import pystacks
    proc = _spawn_child()
    try:
        real = pystacks._python_image_of

        def fake(pid):
            img = real(pid)
            if img and pid == os.getpid():
                path, bias, (dev, ino) = img
                return (path, bias, (dev, ino ^ 1))  # different file
            return img

        monkeypatch.setattr(pystacks, "_python_image_of", fake)
        with pytest.raises(RuntimeError, match="differs from ours"):
            pystacks.RemotePython(proc.pid)
    finally:
        proc.kill()


def test_image_identity_comes_from_target_maps():
    """The identity compared is the (dev, inode) from the TARGET's own
    maps — not a stat() of the path string in our namespace."""
    from deepflow_tpu.agent import pystacks
    proc = _spawn_child()
    try:
        img = pystacks._python_image_of(proc.pid)
        assert img is not None
        access, _bias, ident = img
        assert ident and len(ident) == 2
        # access path routes through the target's root
        assert access.startswith(f"/proc/{proc.pid}/root") or \
            os.path.exists(access)
    finally:
        proc.kill()


# -- extprofiler splice path (needs perf_event_open) -------------------------

def _perf_available() -> bool:
    from deepflow_tpu import native
    lib = native.load()
    if lib is None:
        return False
    from deepflow_tpu.agent.extprofiler import ExternalProfiler
    ExternalProfiler._bind(lib)
    err = ctypes.c_int32(0)
    h = lib.df_prof_open(os.getpid(), 99, 16, ctypes.byref(err))
    if not h:
        return False
    lib.df_prof_close(h)
    return True


needs_perf = pytest.mark.skipif(not _perf_available(),
                                reason="perf_event_open unavailable")


@needs_perf
def test_extprofiler_splices_python_frames():
    """Full mixed-mode path: perf native stacks + spliced qualnames. The
    interpreter-loop libpython run must be replaced by real function
    names; py_spliced/py_threads counters must move."""
    from deepflow_tpu.agent.extprofiler import ExternalProfiler
    proc = _spawn_child()
    try:
        batches = []
        prof = ExternalProfiler(batches.append, pid=proc.pid, hz=99,
                                window_s=0.5, python_stacks=True).start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            time.sleep(0.5)
            if prof.py_spliced and any(
                    "deep_leaf_spin" in s.stack
                    for b in batches for s in b):
                break
        prof.stop()
    finally:
        proc.kill()
    assert prof.py_threads >= 1
    assert prof.py_spliced > 0
    spliced = [s.stack for b in batches for s in b
               if "deep_leaf_spin" in s.stack]
    assert spliced, "no spliced stacks"
    st = spliced[0]
    # root-first: outer_entry before middle_hop before the leaf
    assert st.index("outer_entry") < st.index("middle_hop") \
        < st.index("deep_leaf_spin"), st


@needs_perf
def test_extprofiler_non_python_target_keeps_native():
    """python_stacks=True on a C target: attach fails closed after a few
    windows, native stacks keep flowing, nothing spliced."""
    from deepflow_tpu.agent.extprofiler import ExternalProfiler
    proc = subprocess.Popen(["/bin/sleep", "0.001"])  # placeholder
    proc.wait()
    code = "i=0\nwhile True: i+=1"
    # a busy C-like target without python: use sh arithmetic loop
    proc = subprocess.Popen(
        ["/bin/sh", "-c", "while :; do :; done"],
        stdout=subprocess.DEVNULL)
    try:
        batches = []
        prof = ExternalProfiler(batches.append, pid=proc.pid, hz=99,
                                window_s=0.3, python_stacks=True).start()
        time.sleep(3.0)
        prof.stop()
    finally:
        proc.kill()
    assert prof.py_spliced == 0
    assert not prof._py_enabled  # disabled itself after failed attaches
    total = sum(s.count for b in batches for s in b)
    assert total > 0, "native stacks must keep flowing"
