"""Agent ops surface: remote-exec registry, debug queue taps, upgrade,
plugin API.

Reference analogs: message/agent.proto:18 (remote exec over the sync
plane), agent.proto:9 (upgrade), debug/debugger.rs:111 (queue taps),
plugin/wasm/mod.rs:17 (custom parser hooks). VERDICT round-1 missing #10.
"""

import sys
import time
import types

import pytest

from deepflow_tpu.agent.agent import Agent
from deepflow_tpu.agent.config import AgentConfig
from deepflow_tpu.agent.ops import CommandRegistry, load_plugins


def _local_agent():
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", 1)]
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    return Agent(cfg)


def test_registry_commands_and_unknown():
    agent = _local_agent()
    reg = CommandRegistry(agent)
    code, out = reg.run("help", [])
    assert code == 0 and "queues" in out and "upgrade" in out
    code, out = reg.run("status", [])
    assert code == 0 and "pid" in out
    code, out = reg.run("rm", ["-rf", "/"])  # NOT a shell
    assert code == 127 and "unknown command" in out
    code, out = reg.run("config", [])
    assert code == 0 and "profiler" in out


def test_queue_tap_samples_without_consuming():
    agent = _local_agent()
    from deepflow_tpu.codec import MessageType
    agent.sender.send(MessageType.DFSTATS, b"x" * 100)
    agent.sender.send(MessageType.PROFILE, b"y" * 50)
    reg = CommandRegistry(agent)
    code, out = reg.run("queue-tap", ["5", "sender"])
    assert code == 0
    assert "DFSTATS" in out and "PROFILE" in out
    # tap did not consume
    assert agent.sender.queue_depth() == 2
    code, out = reg.run("queues", [])
    assert code == 0 and '"sender_queue": 2' in out


def test_upgrade_reexecs_via_seam():
    agent = _local_agent()
    reg = CommandRegistry(agent)
    code, out = reg.run("upgrade", ["dry-run"])
    assert code == 0 and "dry_run" in out
    called = []
    reg._execv = lambda exe, argv: called.append((exe, argv))
    code, out = reg.run("upgrade", [])
    assert code == 0 and "upgrading" in out
    deadline = time.time() + 12   # stop() drains the sender first
    while time.time() < deadline and not called:
        time.sleep(0.05)
    assert called and called[0][0] == sys.executable


def test_remote_exec_end_to_end():
    """Controller queues a command; a real syncing agent executes it and
    the result returns over the sync plane to the HTTP API."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from deepflow_tpu.server import Server

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    sync_port=0, enable_controller=True).start()
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.controller = f"127.0.0.1:{server.controller.port}"
    cfg.sync_interval_s = 0.3
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    agent = Agent(cfg).start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and \
                not server.controller.registry.list():
            time.sleep(0.1)
        agents = server.controller.registry.list()
        assert agents
        agent_id = agents[0]["agent_id"]
        cid = server.controller.commands.submit(agent_id, "queues", [])
        deadline = time.time() + 10
        result = None
        while time.time() < deadline:
            result = server.controller.commands.result(cid)
            if result and result["state"] == "done":
                break
            time.sleep(0.1)
        assert result and result["state"] == "done", result
        assert result["exit_code"] == 0
        assert "sender_queue" in result["output"]
        # the HTTP surface wraps the same queue
        from deepflow_tpu.server.querier import QuerierAPI  # noqa: F401
        out = server.api.agent_exec({"agent_id": agent_id, "cmd": "status"})
        cid2 = out["result_id"]
        deadline = time.time() + 10
        while time.time() < deadline:
            r = server.api.agent_exec({"result_id": cid2})["result"]
            if r["state"] == "done":
                break
            time.sleep(0.1)
        assert r["state"] == "done" and "components" in r["output"]
    finally:
        agent.stop()
        server.stop()


def test_parser_plugin_loads_and_wins():
    """A plugin module's parser registers ahead of builtins and parses a
    custom protocol through the normal flow path."""
    from deepflow_tpu.agent.protocol_logs.base import (
        MSG_REQUEST, REGISTRY, L7ParseResult, L7Parser, infer_and_parse)
    from deepflow_tpu.proto import pb

    mod = types.ModuleType("df_test_plugin")

    class ToyParser(L7Parser):
        PROTOCOL = pb.HTTP1  # piggyback an id; plugins may reuse or extend
        NAME = "toy"

        def check(self, payload, port_dst=0):
            return payload.startswith(b"TOY/")

        def parse(self, payload, is_request=True):
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type="TOY",
                request_resource=payload[4:12].decode("latin1"))]

    mod.PARSERS = [ToyParser]
    sys.modules["df_test_plugin"] = mod
    before = len(REGISTRY)
    try:
        loaded = load_plugins(["df_test_plugin"])
        assert loaded == ["df_test_plugin.ToyParser"]
        proto, recs = infer_and_parse(b"TOY/widgets")
        assert recs and recs[0].request_type == "TOY"
        assert recs[0].request_resource == "widgets"
    finally:
        del sys.modules["df_test_plugin"]
        del REGISTRY[0: len(REGISTRY) - before]


def test_pcap_capture_ships_to_server():
    """On-demand pcap capture (reference: ingester pcap module): the
    command captures live frames, ships them, the server stores and
    serves them for download."""
    import base64
    import gzip
    import socket as _s
    import threading
    try:
        probe = _s.socket(_s.AF_PACKET, _s.SOCK_RAW)
        probe.close()
    except (PermissionError, AttributeError, OSError):
        pytest.skip("no CAP_NET_RAW")
    from deepflow_tpu.server import Server
    from deepflow_tpu.agent.packet import read_pcap_records

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    agent = Agent(cfg).start()
    reg = CommandRegistry(agent)
    try:
        # traffic generator during the capture window
        stopgen = threading.Event()

        def gen():
            while not stopgen.is_set():
                s = _s.socket(_s.AF_INET, _s.SOCK_DGRAM)
                s.sendto(b"ping", ("127.0.0.1", 19999))
                s.close()
                time.sleep(0.02)

        threading.Thread(target=gen, daemon=True).start()
        code, out = reg.run("pcap-capture", ["1.5", "lo"])
        stopgen.set()
        assert code == 0, out
        import json as _json
        meta = _json.loads(out)
        assert meta["packets"] > 0
        deadline = time.time() + 10
        while time.time() < deadline and \
                not getattr(server.db, "pcap_store", {"entries": []}
                            )["entries"]:
            time.sleep(0.1)
        listing = server.api.pcaps()["pcaps"]
        assert listing and listing[0]["name"] == meta["name"]
        dl = server.api.pcaps({"name": meta["name"]})
        raw = gzip.decompress(base64.b64decode(dl["pcap_gz_b64"]))
        import tempfile
        with tempfile.NamedTemporaryFile(suffix=".pcap") as f:
            f.write(raw)
            f.flush()
            recs = read_pcap_records(f.name)
        assert len(recs) == meta["packets"]
    finally:
        agent.stop()
        server.stop()


def test_config_template_roundtrip():
    """The generated template parses, validates, and matches defaults
    (the dataclass is the single source of truth — no drift possible)."""
    import yaml
    from dataclasses import asdict
    from deepflow_tpu.agent.config import render_template
    text = render_template()
    data = yaml.safe_load(text)
    cfg = AgentConfig.from_dict(data).validate()
    assert asdict(cfg) == asdict(AgentConfig())
    # checked-in copy stays current
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "agent-template.yaml")
    assert open(path).read() == text, \
        "regenerate docs/agent-template.yaml (render_template changed)"
