"""Agent ops surface: remote-exec registry, debug queue taps, upgrade,
plugin API.

Reference analogs: message/agent.proto:18 (remote exec over the sync
plane), agent.proto:9 (upgrade), debug/debugger.rs:111 (queue taps),
plugin/wasm/mod.rs:17 (custom parser hooks). VERDICT round-1 missing #10.
"""

import sys
import time
import types

import pytest

from deepflow_tpu.agent.agent import Agent
from deepflow_tpu.agent.config import AgentConfig
from deepflow_tpu.agent.ops import CommandRegistry, load_plugins


def _local_agent():
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", 1)]
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    return Agent(cfg)


def test_registry_commands_and_unknown():
    agent = _local_agent()
    reg = CommandRegistry(agent)
    code, out = reg.run("help", [])
    assert code == 0 and "queues" in out and "upgrade" in out
    code, out = reg.run("status", [])
    assert code == 0 and "pid" in out
    code, out = reg.run("rm", ["-rf", "/"])  # NOT a shell
    assert code == 127 and "unknown command" in out
    code, out = reg.run("config", [])
    assert code == 0 and "profiler" in out


def test_queue_tap_samples_without_consuming():
    agent = _local_agent()
    from deepflow_tpu.codec import MessageType
    agent.sender.send(MessageType.DFSTATS, b"x" * 100)
    agent.sender.send(MessageType.PROFILE, b"y" * 50)
    reg = CommandRegistry(agent)
    code, out = reg.run("queue-tap", ["5", "sender"])
    assert code == 0
    assert "DFSTATS" in out and "PROFILE" in out
    # tap did not consume
    assert agent.sender.queue_depth() == 2
    code, out = reg.run("queues", [])
    assert code == 0 and '"sender_queue": 2' in out


def test_upgrade_reexecs_via_seam():
    agent = _local_agent()
    reg = CommandRegistry(agent)
    code, out = reg.run("upgrade", ["dry-run"])
    assert code == 0 and "dry_run" in out
    called = []
    reg._execv = lambda exe, argv: called.append((exe, argv))
    code, out = reg.run("upgrade", [])
    assert code == 0 and "upgrading" in out
    deadline = time.time() + 12   # stop() drains the sender first
    while time.time() < deadline and not called:
        time.sleep(0.05)
    assert called and called[0][0] == sys.executable


def test_remote_exec_end_to_end():
    """Controller queues a command; a real syncing agent executes it and
    the result returns over the sync plane to the HTTP API."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from deepflow_tpu.server import Server

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    sync_port=0, enable_controller=True).start()
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.controller = f"127.0.0.1:{server.controller.port}"
    cfg.sync_interval_s = 0.3
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    agent = Agent(cfg).start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and \
                not server.controller.registry.list():
            time.sleep(0.1)
        agents = server.controller.registry.list()
        assert agents
        agent_id = agents[0]["agent_id"]
        cid = server.controller.commands.submit(agent_id, "queues", [])
        deadline = time.time() + 10
        result = None
        while time.time() < deadline:
            result = server.controller.commands.result(cid)
            if result and result["state"] == "done":
                break
            time.sleep(0.1)
        assert result and result["state"] == "done", result
        assert result["exit_code"] == 0
        assert "sender_queue" in result["output"]
        # the HTTP surface wraps the same queue
        from deepflow_tpu.server.querier import QuerierAPI  # noqa: F401
        out = server.api.agent_exec({"agent_id": agent_id, "cmd": "status"})
        cid2 = out["result_id"]
        deadline = time.time() + 10
        while time.time() < deadline:
            r = server.api.agent_exec({"result_id": cid2})["result"]
            if r["state"] == "done":
                break
            time.sleep(0.1)
        assert r["state"] == "done" and "components" in r["output"]
    finally:
        agent.stop()
        server.stop()


def test_parser_plugin_loads_and_wins():
    """A plugin module's parser registers ahead of builtins and parses a
    custom protocol through the normal flow path."""
    from deepflow_tpu.agent.protocol_logs.base import (
        MSG_REQUEST, REGISTRY, L7ParseResult, L7Parser, infer_and_parse)
    from deepflow_tpu.proto import pb

    mod = types.ModuleType("df_test_plugin")

    class ToyParser(L7Parser):
        PROTOCOL = pb.HTTP1  # piggyback an id; plugins may reuse or extend
        NAME = "toy"

        def check(self, payload, port_dst=0):
            return payload.startswith(b"TOY/")

        def parse(self, payload, is_request=True):
            return [L7ParseResult(
                l7_protocol=self.PROTOCOL, msg_type=MSG_REQUEST,
                request_type="TOY",
                request_resource=payload[4:12].decode("latin1"))]

    mod.PARSERS = [ToyParser]
    sys.modules["df_test_plugin"] = mod
    before = len(REGISTRY)
    try:
        loaded = load_plugins(["df_test_plugin"])
        assert loaded == ["df_test_plugin.ToyParser"]
        proto, recs = infer_and_parse(b"TOY/widgets")
        assert recs and recs[0].request_type == "TOY"
        assert recs[0].request_resource == "widgets"
    finally:
        del sys.modules["df_test_plugin"]
        del REGISTRY[0: len(REGISTRY) - before]
