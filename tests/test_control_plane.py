"""Control plane tests: gRPC sync, config push, GPID, tag injection."""

import time

import pytest

from deepflow_tpu.agent.agent import Agent
from deepflow_tpu.agent.config import AgentConfig
from deepflow_tpu.proto import pb
from deepflow_tpu.server import Server


@pytest.fixture
def server():
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0, sync_port=0,
               enable_controller=True).start()
    yield s
    s.stop()


def make_agent(server, **kw):
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.controller = f"127.0.0.1:{server.controller.port}"
    cfg.standalone = False
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.sync_interval_s = 0.2
    for k, v in kw.items():
        setattr(cfg, k, v)
    return Agent(cfg)


def test_sync_assigns_agent_id_and_platform(server):
    agent = make_agent(server).start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["syncs"] == 0:
            time.sleep(0.05)
        assert agent.synchronizer.stats["syncs"] >= 1
        assert agent.config.agent_id == 1
        assert agent.sender.agent_id == 1
        # platform data reached the ingester tag table
        info = server.platform.query(1)
        assert info.host  # hostname recorded
        agents = server.controller.registry.list()
        assert len(agents) == 1 and agents[0]["agent_id"] == 1
    finally:
        agent.stop()


def test_config_push_hot_applies(server):
    agent = make_agent(server).start()
    agent.config.profiler.enabled = True  # pretend sampler running
    from deepflow_tpu.agent.profiler import OnCpuSampler
    agent.sampler = OnCpuSampler(lambda b: None, hz=99.0)
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["config_updates"] == 0:
            time.sleep(0.05)
        assert agent.synchronizer.config_version == 1

        new_yaml = b"profiler:\n  sample_hz: 250.0\n  emit_interval_s: 0.5\n"
        v = server.controller.configs.update("default", new_yaml)
        assert v == 2
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.config_version != 2:
            time.sleep(0.05)
        assert agent.synchronizer.config_version == 2
        assert agent.config.profiler.sample_hz == 250.0
        assert agent.sampler.period_us == 4000
    finally:
        agent.stop()


def test_config_validation_rejects_garbage(server):
    with pytest.raises(Exception):
        server.controller.configs.update("default", b"- just\n- a list\n")
    with pytest.raises(Exception):
        server.controller.configs.update(
            "default", b"profiler:\n  sample_hz: not_a_number\n")


def test_gpid_sync(server):
    agent = make_agent(server).start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["syncs"] == 0:
            time.sleep(0.05)
        e = pb.GpidEntry()
        e.pid = 4242
        e.ip = b"\x0a\x00\x00\x01"
        e.port = 8080
        e.proto = pb.TCP
        e.role = 1
        resp = agent.synchronizer.gpid_sync([e])
        assert len(resp.entries) == 1
        assert resp.entries[0].gpid > 0
        # same (agent, pid) keeps its gpid
        resp2 = agent.synchronizer.gpid_sync([e])
        assert resp2.entries[0].gpid == resp.entries[0].gpid
    finally:
        agent.stop()


def test_tag_injection_uses_sync_platform(server):
    """Rows ingested after sync carry the host tag from platform data."""
    agent = make_agent(server).start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["syncs"] == 0:
            time.sleep(0.05)
        batch = pb.EventBatch()
        ev = batch.events.add()
        ev.event_type = "test"
        ev.timestamp_ns = time.time_ns()
        from deepflow_tpu.codec import MessageType
        agent.sender.send(MessageType.EVENT, batch.SerializeToString())
        assert server.wait_for_rows("event.event", 1)
        t = server.db.table("event.event")
        cols = t.column_concat(["host", "agent_id"])
        host = t.dicts["host"].decode(int(cols["host"][0]))
        assert host != ""
        assert cols["agent_id"].tolist() == [1]
    finally:
        agent.stop()


def test_group_config_routing(server):
    server.controller.configs.update("prod", b"profiler:\n  sample_hz: 42.0\n")
    agent = make_agent(server, group="prod")
    agent.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["config_updates"] == 0:
            time.sleep(0.05)
        assert agent.config.profiler.sample_hz == 42.0
    finally:
        agent.stop()


def test_enable_flag_hot_applies(server):
    agent = make_agent(server).start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["config_updates"] == 0:
            time.sleep(0.05)
        # default config enables the profiler -> sampler was started
        assert agent.sampler is not None
        server.controller.configs.update(
            "default", b"profiler:\n  enabled: false\n"
                       b"tpuprobe:\n  enabled: false\n")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and agent.sampler is not None:
            time.sleep(0.05)
        assert agent.sampler is None
    finally:
        agent.stop()


def test_controller_restart_recovers_platform(server):
    agent = make_agent(server).start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["syncs"] == 0:
            time.sleep(0.05)
        # simulate controller state loss
        server.controller._platforms.clear()
        server.platform._agents.clear()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                not server.platform.query(1).host:
            time.sleep(0.05)
        assert server.platform.query(1).host  # repopulated by re-sent sync
    finally:
        agent.stop()
