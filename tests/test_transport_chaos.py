"""Loss-bounded transport: spool, seq/ACK, dedup, priority shedding, chaos.

Every test here is about one claim: a frame handed to the durable sender
either lands in a server table exactly once, or its loss is accounted on
a ledger with a named reason — across queue overflow, connection faults,
and a full server kill-and-recover.
"""

import os
import socket
import struct
import tempfile
import time

import pytest

from deepflow_tpu.codec import (
    PRIORITY_HIGH, PRIORITY_LOW, PRIORITY_MID, FrameHeader, MessageType,
    decode_ack, decode_frame, encode_ack, encode_frame, priority_of)
from deepflow_tpu.proto import pb
from deepflow_tpu.server import Server
from deepflow_tpu.telemetry import Telemetry

MS = 1_000_000


@pytest.fixture
def server():
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    yield s
    s.stop()


def _event_payload(name: str = "x") -> bytes:
    batch = pb.EventBatch()
    e = batch.events.add()
    e.event_type = "chaos-test"
    e.resource_name = name
    e.timestamp_ns = time.time_ns()
    return batch.SerializeToString()


def _step_payload(i: int) -> bytes:
    from deepflow_tpu.tpuprobe.stepmetrics import encode_step_payload
    return encode_step_payload([{
        "time": i * MS, "end_ns": i * MS + 500, "latency_ns": 500,
        "run_id": 3, "step": i, "job": "t", "device_count": 4,
        "device_skew_ns": 0, "compute_ns": 1, "collective_ns": 1,
        "straggler_device": 0, "straggler_lag_ns": 0, "top_hlos": []}])


def _stats_payload() -> bytes:
    batch = pb.StatsBatch()
    m = batch.metrics.add()
    m.name = "noise"
    m.timestamp_ns = time.time_ns()
    m.values["v"] = 1.0
    return batch.SerializeToString()


def _ledger(telemetry, hop_name):
    for h in telemetry.snapshot()["pipeline"]:
        if h["hop"] == hop_name:
            return h
    raise AssertionError(f"no hop {hop_name!r}")


def _assert_balanced(h):
    assert h["emitted"] == h["delivered"] + h["dropped_total"] \
        + h["in_flight"], h


# -- codec: v2 seq extension + ACK frames -------------------------------------

def test_codec_v2_roundtrip_and_v1_backcompat():
    v2 = encode_frame(
        FrameHeader(MessageType.L7_LOG, agent_id=7, seq=123456789), b"pay")
    h, p, consumed = decode_frame(v2)
    assert (h.seq, h.agent_id, p, consumed) == (123456789, 7, b"pay", len(v2))

    v1 = encode_frame(FrameHeader(MessageType.L7_LOG, agent_id=7), b"pay")
    h1, p1, _ = decode_frame(v1)
    assert h1.seq is None and p1 == b"pay"
    # a seq-less header must produce a byte-identical v1 frame: old
    # decoders keep working, and the wire only changes when seq is used
    assert v1[6] == 1 and v2[6] == 2


def test_codec_v2_compressed_carries_seq():
    big = b"z" * 4096  # above the compress threshold
    frame = encode_frame(
        FrameHeader(MessageType.PROFILE, agent_id=2, seq=99), big)
    assert len(frame) < len(big)
    h, p, _ = decode_frame(frame)
    assert h.seq == 99 and h.compressed and p == big


def test_ack_frame_roundtrip():
    h, payload, _ = decode_frame(encode_ack(12, 3456))
    assert h.msg_type == MessageType.ACK
    assert h.agent_id == 12
    assert decode_ack(payload) == 3456


def test_priority_classes():
    assert priority_of(MessageType.STEP_METRICS) == PRIORITY_HIGH
    assert priority_of(MessageType.L7_LOG) == PRIORITY_HIGH
    assert priority_of(MessageType.METRICS) == PRIORITY_MID
    assert priority_of(MessageType.DFSTATS) == PRIORITY_LOW


# -- spool: segmented on-disk overflow ----------------------------------------

def test_spool_spill_replay_trim(tmp_path):
    from deepflow_tpu.agent.spool import Spool
    sp = Spool(str(tmp_path), max_bytes=1 << 20, segment_bytes=32 << 10)
    for i in range(1, 201):
        assert sp.append(int(MessageType.L7_LOG), i, b"p" * 64)
    assert sp.pending_records() == 200
    assert [s for _, s, _ in sp.replay(150)] == list(range(151, 201))
    sp.trim(199)
    sp.close()
    # a fresh Spool over the same dir recovers what was not trimmed
    sp2 = Spool(str(tmp_path), max_bytes=1 << 20, segment_bytes=32 << 10)
    assert all(s > 150 for _, s, _ in sp2.replay(150))
    assert sp2.max_seq() == 200
    sp2.close()


def test_spool_evicts_oldest_segment_at_cap(tmp_path):
    from deepflow_tpu.agent.spool import Spool
    evicted = []
    sp = Spool(str(tmp_path), max_bytes=8 << 10, segment_bytes=2 << 10,
               on_evict=lambda n, reason: evicted.append((n, reason)))
    for i in range(1, 501):
        sp.append(int(MessageType.L7_LOG), i, b"p" * 64)
    assert sp.pending_bytes() <= 8 << 10
    assert evicted and all(r == "spool_evict" for _, r in evicted)
    # the survivors are the NEWEST records
    seqs = [s for _, s, _ in sp.replay(0)]
    assert seqs == sorted(seqs) and seqs[-1] == 500
    assert sp.stats["evicted"] == sum(n for n, _ in evicted)
    sp.close()


def test_spool_out_of_order_append_trim_safe(tmp_path):
    """The sender's OSError respool path can append an OLDER in-flight
    seq after newer overflow spills; trim() must see the segment's true
    max (the old arrival-order last_seq let an ack for the low seq
    delete the unacked high record)."""
    from deepflow_tpu.agent.spool import Spool
    sp = Spool(str(tmp_path), segment_bytes=4096)
    big = b"p" * 3000
    assert sp.append(int(MessageType.L7_LOG), 5000, big)
    assert sp.append(int(MessageType.L7_LOG), 100, b"respooled")
    assert sp.append(int(MessageType.L7_LOG), 5001, big)  # rotates
    assert sp.max_seq() == 5001
    assert sp.min_pending_seq() == 100
    sp.trim(100)  # ack covering only the low seq: nothing may go
    assert sp.pending_records() == 3
    assert sorted(s for _, s, _ in sp.replay(100)) == [5000, 5001]
    sp.trim(5000)  # now the whole first segment is covered
    assert [s for _, s, _ in sp.replay(0)] == [5001]
    sp.close()
    # recovery rebuilds true min/max from the surviving records
    sp2 = Spool(str(tmp_path), segment_bytes=4096)
    assert sp2.max_seq() == 5001
    assert sp2.min_pending_seq() == 5001
    sp2.close()


def test_spool_recovers_through_torn_tail(tmp_path):
    from deepflow_tpu.agent.spool import Spool
    sp = Spool(str(tmp_path))
    for i in range(1, 11):
        sp.append(int(MessageType.L7_LOG), i, b"q" * 32)
    sp.close()
    seg = sorted(os.listdir(tmp_path))[-1]
    path = os.path.join(str(tmp_path), seg)
    with open(path, "r+b") as f:  # tear the last record mid-payload
        f.truncate(os.path.getsize(path) - 7)
    sp2 = Spool(str(tmp_path))
    seqs = [s for _, s, _ in sp2.replay(0)]
    assert seqs == list(range(1, 10))  # record 10 gone, 1..9 intact
    sp2.close()


# -- receiver: SeqAckTracker ---------------------------------------------------

def test_seq_tracker_contiguous_and_out_of_order():
    from deepflow_tpu.server.receiver import SeqAckTracker
    t = SeqAckTracker()
    t.observe(1, 1)
    t.observe(1, 2)
    assert t.contiguous(1) == 2
    t.observe(1, 5)          # gap: 3,4 missing
    assert t.contiguous(1) == 2
    t.observe(1, 4)
    t.observe(1, 3)          # gap fills -> window absorbs the parked oos
    assert t.contiguous(1) == 5
    t.observe(1, 2)          # stale dup: no effect
    assert t.contiguous(1) == 5
    assert t.contiguous(2) is None


def test_seq_tracker_gap_jump_on_oos_overflow():
    from deepflow_tpu.server.receiver import SeqAckTracker
    t = SeqAckTracker()
    t.observe(1, 1)
    # seq 2 never arrives (it was dropped WITH accounting); the window
    # must not stall forever behind it
    for s in range(3, 3 + SeqAckTracker.MAX_OOS + 1):
        t.observe(1, s)
    assert t.contiguous(1) >= 3


def test_seq_tracker_seed_floor():
    from deepflow_tpu.server.receiver import SeqAckTracker
    t = SeqAckTracker()
    t.seed(1, 100)
    t.observe(1, 101)
    assert t.contiguous(1) == 101


def test_seq_tracker_advance_forward_only():
    """SEQ_BASE handling: a declared-dead gap fast-forwards the
    watermark, absorbs parked seqs, and never moves backward."""
    from deepflow_tpu.server.receiver import SeqAckTracker
    t = SeqAckTracker()
    t.observe(1, 1)
    t.observe(1, 5)           # parks out of order behind the 2..4 gap
    t.advance(1, 3)           # agent: 2..3 will never be sent
    assert t.contiguous(1) == 3
    t.observe(1, 4)           # gap closes -> parked 5 drains in
    assert t.contiguous(1) == 5
    t.advance(1, 2)           # backward announce: ignored
    assert t.contiguous(1) == 5
    t.advance(7, 100)         # unseen agent: seeds the window
    assert t.contiguous(7) == 100


# -- decoders: dedup window ----------------------------------------------------

def test_dedup_window_per_agent_floors_and_contiguity():
    """Per-agent windows: one agent's traffic can never evict another
    agent's still-live entries (the old shared LRU could, reopening a
    dup hole under retransmit)."""
    from deepflow_tpu.server.decoders import DedupWindow
    w = DedupWindow(capacity=4, floors={1: 10})
    assert w.seen(1, 10)            # at/under the floor: dup
    assert not w.seen(1, 11)
    assert w.seen(1, 11)            # second sight: dup
    for s in range(12, 200):        # dense stream: floor tracks it
        assert not w.seen(1, s)
    # far more than `capacity` agent-2 seqs cannot evict agent 1's state
    for s in range(1, 50):
        assert not w.seen(2, s)
    assert w.seen(1, 150)           # still remembered (old LRU forgot)
    assert w.seen(1, 199)
    assert w.seen(2, 49)


def test_dedup_window_floor_jump_on_unannounced_gap():
    """An un-announced permanent gap must not grow the park set without
    bound: past capacity the floor jumps to the oldest parked seq."""
    from deepflow_tpu.server.decoders import DedupWindow
    w = DedupWindow(capacity=4)
    assert not w.seen(1, 1)
    for s in range(3, 9):           # seq 2 never arrives
        assert not w.seen(1, s)
    assert w.stats["floor_jumps"] >= 1
    assert w.seen(1, 5)             # absorbed by the jump: still a dup


def test_dedup_window_advance_floor_forward_only():
    from deepflow_tpu.server.decoders import DedupWindow
    w = DedupWindow()
    assert not w.seen(1, 5)   # parks above the floor
    w.advance_floor(1, 4)     # SEQ_BASE: 1..4 dead -> parked 5 absorbed
    assert w.seen(1, 3)
    assert w.seen(1, 5)
    w.advance_floor(1, 2)     # backward: ignored
    assert w.seen(1, 3)
    assert not w.seen(1, 6)


def test_dedup_under_forced_retransmit(server):
    """The same v2 frame written twice (a retransmit whose original DID
    land) must produce ONE row, with the dup accounted dropped(dup)."""
    frame = encode_frame(
        FrameHeader(MessageType.EVENT, agent_id=4, seq=1),
        _event_payload("once"))
    s = socket.create_connection(("127.0.0.1", server.ingest_port))
    s.sendall(frame)
    s.sendall(frame)
    s.close()
    assert server.wait_for_rows("event.event", 1)
    dec = next(d for d in server.decoders
               if d.MSG_TYPE == MessageType.EVENT)
    deadline = time.time() + 5
    while time.time() < deadline and dec.stats["dups"] < 1:
        time.sleep(0.02)
    assert dec.stats["dups"] == 1
    assert len(server.db.table("event.event")) == 1
    h = _ledger(server.telemetry, "decoder.EVENT")
    assert h["dropped"].get("dup") == 1
    _assert_balanced(h)


def test_receiver_acks_flow_back(server):
    """A raw v2 writer must get ACK frames back on the same socket."""
    s = socket.create_connection(("127.0.0.1", server.ingest_port))
    for seq in range(1, 6):
        s.sendall(encode_frame(
            FrameHeader(MessageType.EVENT, agent_id=6, seq=seq),
            _event_payload(f"e{seq}")))
    s.settimeout(5.0)
    buf = b""
    acked = 0
    while acked < 5:
        buf += s.recv(4096)
        # drain EVERY complete frame before reading again: one recv can
        # carry several concatenated ACKs
        while True:
            h, payload, consumed = decode_frame(buf)
            if not consumed:
                break
            assert h.msg_type == MessageType.ACK and h.agent_id == 6
            acked = decode_ack(payload)
            buf = buf[consumed:]
    s.close()
    assert acked == 5


def test_v1_sender_gets_no_acks(server):
    """Seq-less (v1) writers must NOT be sent ACK frames: a pre-ACK
    peer would see them as garbage on a previously write-only socket."""
    s = socket.create_connection(("127.0.0.1", server.ingest_port))
    s.sendall(encode_frame(FrameHeader(MessageType.EVENT, agent_id=6),
                           _event_payload()))
    assert server.wait_for_rows("event.event", 1)
    time.sleep(0.3)
    s.settimeout(0.2)
    with pytest.raises(socket.timeout):
        s.recv(1)
    s.close()


# -- sender: failover, spool spill/replay, ack trim, shedding -----------------

def test_sender_failover_dead_then_live(server):
    """In-flight frames must survive the dead first server (satellite:
    the old sender counted an in-flight OSError frame as dropped)."""
    from deepflow_tpu.agent.sender import UniformSender
    tel = Telemetry("agent", enabled=True)
    sender = UniformSender(
        [("127.0.0.1", 1), ("127.0.0.1", server.ingest_port)],
        agent_id=9, telemetry=tel).start()
    for i in range(20):
        assert sender.send(MessageType.EVENT, _event_payload(f"e{i}"))
    assert server.wait_for_rows("event.event", 20)
    sender.flush_and_stop()
    h = _ledger(tel, "sender")
    assert h["emitted"] == 20 and h["delivered"] == 20
    assert h["dropped_total"] == 0
    assert len(server.db.table("event.event")) == 20


def test_sender_spools_overflow_and_replays(server):
    """Queue overflow while the server is down: HIGH frames spill to
    disk, replay once the server is reachable, ledger stays balanced."""
    from deepflow_tpu.agent.sender import UniformSender
    from deepflow_tpu.agent.spool import Spool
    tel = Telemetry("agent", enabled=True)
    spool_dir = tempfile.mkdtemp(prefix="df-test-spool-")
    # port 1: nothing listening. Tiny queue so sends overflow fast.
    sender = UniformSender(
        [("127.0.0.1", 1)], agent_id=9, queue_size=4,
        spool=Spool(spool_dir), telemetry=tel).start()
    n = 50
    for i in range(1, n + 1):
        assert sender.send(MessageType.STEP_METRICS, _step_payload(i))
    assert sender.stats["spooled"] >= n - 5  # almost all spilled
    # point the sender at the live server: failover + replay
    sender.servers.append(("127.0.0.1", server.ingest_port))
    assert server.wait_for_rows("profile.tpu_step_metrics", n, timeout=15)
    sender.flush_and_stop(timeout=10)
    assert sender.stats["replayed"] >= sender.stats["spooled"] > 0
    h = _ledger(tel, "sender")
    assert h["emitted"] == n and h["delivered"] == n
    assert h["dropped_total"] == 0 and h["in_flight"] == 0
    assert len(server.db.table("profile.tpu_step_metrics")) == n


def test_ack_trims_retransmit_window_and_spool(server):
    from deepflow_tpu.agent.sender import UniformSender
    from deepflow_tpu.agent.spool import Spool
    spool_dir = tempfile.mkdtemp(prefix="df-test-spool-")
    sender = UniformSender(
        [("127.0.0.1", server.ingest_port)], agent_id=9,
        spool=Spool(spool_dir)).start()
    n = 30
    for i in range(1, n + 1):
        sender.send(MessageType.EVENT, _event_payload(f"e{i}"))
    assert server.wait_for_rows("event.event", n)
    # seqs start at the boot's epoch base, not 1
    target = sender.seq_base + n
    deadline = time.time() + 5
    while time.time() < deadline and sender.stats["acked_seq"] < target:
        time.sleep(0.02)
    assert sender.stats["acked_seq"] == target
    assert not sender._unacked and not sender._pending
    assert sender.spool.pending_records() == 0
    sender.flush_and_stop()


def test_priority_shed_order():
    """On overflow the sender sheds LOW (dfstats) before MID (metrics)
    and never HIGH — each shed accounted dropped(priority_shed_*)."""
    from deepflow_tpu.agent.sender import UniformSender
    tel = Telemetry("agent", enabled=True)
    # not started: nothing drains the queue, so occupancy is exact
    sender = UniformSender([("127.0.0.1", 1)], agent_id=9, queue_size=4,
                           telemetry=tel)
    for _ in range(2):
        assert sender.send(MessageType.DFSTATS, b"low")
    for _ in range(2):
        assert sender.send(MessageType.METRICS, b"mid")
    # queue full of 2 LOW + 2 MID; HIGH sends must displace LOW first
    assert sender.send(MessageType.L7_LOG, b"high1")
    assert sender.send(MessageType.L7_LOG, b"high2")
    # then MID
    assert sender.send(MessageType.L7_LOG, b"high3")
    h = _ledger(tel, "sender")
    assert h["dropped"] == {"priority_shed_low": 2, "priority_shed_mid": 1}
    queued = [f.msg_type for f in sender._q.queue]
    assert queued.count(MessageType.L7_LOG) == 3
    assert MessageType.DFSTATS not in queued
    # a MID send with only MID/HIGH queued: sheds nothing, drops itself
    assert not sender.send(MessageType.METRICS, b"mid2")
    h = _ledger(tel, "sender")
    assert h["dropped"]["queue_full_mid"] == 1
    _assert_balanced(h)


def test_low_priority_drop_is_accounted_without_spool():
    from deepflow_tpu.agent.sender import UniformSender
    tel = Telemetry("agent", enabled=True)
    sender = UniformSender([("127.0.0.1", 1)], agent_id=9, queue_size=2,
                           telemetry=tel)
    for _ in range(2):
        assert sender.send(MessageType.DFSTATS, b"low")
    assert not sender.send(MessageType.DFSTATS, b"low-overflow")
    h = _ledger(tel, "sender")
    assert h["dropped"] == {"queue_full_low": 1}
    _assert_balanced(h)


def test_shed_and_drop_burn_no_seq():
    """A frame dropped before reaching the wire or spool must not
    consume a seq: a burned seq is a permanent gap that stalls the
    server's contiguous watermark (and with it every ack)."""
    from deepflow_tpu.agent.sender import UniformSender
    # not started: nothing drains the queue, no wire writes happen
    sender = UniformSender([("127.0.0.1", 1)], agent_id=9, queue_size=2)
    first = sender._next_seq
    for _ in range(2):
        assert sender.send(MessageType.DFSTATS, b"low")
    assert not sender.send(MessageType.DFSTATS, b"low")  # queue_full drop
    assert sender.send(MessageType.L7_LOG, b"high")      # sheds a LOW
    assert sender._next_seq == first


def test_seq_base_fast_forwards_ack_watermark(server):
    """A SEQ_BASE control frame (restarted agent adopting a fresh epoch
    seq space) must jump the ack watermark past the never-sent gap —
    without it the tracker parks the new epoch's seqs as out-of-order
    and acks stall at the old boot's high-water mark."""
    from deepflow_tpu.codec import encode_seq_base

    def read_acks_until(s, buf, target, timeout=5.0):
        s.settimeout(timeout)
        acked = 0
        deadline = time.time() + timeout
        while acked < target and time.time() < deadline:
            buf += s.recv(4096)
            while True:
                h, payload, consumed = decode_frame(buf)
                if not consumed:
                    break
                assert h.msg_type == MessageType.ACK
                acked = decode_ack(payload)
                buf = buf[consumed:]
        return acked, buf

    s = socket.create_connection(("127.0.0.1", server.ingest_port))
    s.sendall(encode_frame(
        FrameHeader(MessageType.EVENT, agent_id=8, seq=1),
        _event_payload("old-boot")))
    acked, buf = read_acks_until(s, b"", 1)
    assert acked == 1
    # "restart": everything below the new epoch base is acked or dead
    base = 1 << 32
    s.sendall(encode_seq_base(8, base))
    s.sendall(encode_frame(
        FrameHeader(MessageType.EVENT, agent_id=8, seq=base),
        _event_payload("new-boot")))
    acked, _ = read_acks_until(s, buf, base)
    s.close()
    assert acked == base
    assert server.wait_for_rows("event.event", 2)
    assert len(server.db.table("event.event")) == 2


def test_agent_restart_same_id_not_deduped(server):
    """A restarted agent reuses its agent_id with a fresh epoch-seeded
    seq space; the server must adopt it instead of dup-dropping every
    frame against the old boot's watermark (the old always-from-1
    counter lost ALL post-restart traffic this way)."""
    from deepflow_tpu.agent.sender import UniformSender
    n = 15
    bases = []
    for boot in range(2):
        sender = UniformSender([("127.0.0.1", server.ingest_port)],
                               agent_id=11).start()
        bases.append(sender.seq_base)
        for i in range(n):
            assert sender.send(MessageType.EVENT,
                               _event_payload(f"boot{boot}-{i}"))
        assert server.wait_for_rows("event.event", n * (boot + 1),
                                    timeout=10)
        sender.flush_and_stop()
    assert bases[1] > bases[0]  # the second boot's epoch is above
    assert len(server.db.table("event.event")) == 2 * n


def test_shutdown_backoff_is_interruptible():
    """flush_and_stop on a dead-server sender must return promptly (the
    old backoff slept uninterruptibly for up to 5s per cycle)."""
    from deepflow_tpu.agent.sender import UniformSender
    sender = UniformSender([("127.0.0.1", 1)], agent_id=9).start()
    time.sleep(0.5)  # let the backoff grow past the old 0.1s floor
    t0 = time.monotonic()
    sender.flush_and_stop(timeout=0.2)
    assert time.monotonic() - t0 < 3.0


# -- receiver: UDP trailing garbage (satellite) -------------------------------

def test_udp_trailing_garbage_counted_frame_kept(server):
    frame = encode_frame(FrameHeader(MessageType.EVENT, agent_id=3),
                         _event_payload("udp"))
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.sendto(frame + b"\x00garbage\xff", ("127.0.0.1", server.ingest_port))
    s.close()
    assert server.wait_for_rows("event.event", 1)
    deadline = time.time() + 5
    while time.time() < deadline \
            and server.receiver.stats["udp_trailing_garbage"] < 1:
        time.sleep(0.02)
    assert server.receiver.stats["udp_trailing_garbage"] == 1
    assert server.receiver.stats["bad_frames"] == 1
    h = _ledger(server.telemetry, "receiver")
    assert h["dropped"].get("udp_trailing_garbage") == 1
    _assert_balanced(h)


# -- chaos: seeded kill-and-recover e2e ---------------------------------------

def test_chaos_kill_and_recover_exactly_once():
    """The acceptance scenario, in-process: seeded faults + a server
    kill-and-restart, zero STEP_METRICS loss, zero duplicate rows."""
    from deepflow_tpu.cli import chaos_check
    assert chaos_check.main() == 0
