import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepflow_tpu.models.llama import (
    LlamaConfig, forward, init_params, loss_fn, make_train_step, param_specs)
from deepflow_tpu.parallel import make_mesh, ring_attention, shard_params
from deepflow_tpu.parallel.mesh import factor_devices, named_sharding_tree


def test_factor_devices():
    assert factor_devices(8) == (1, 2, 4)
    assert factor_devices(1) == (1, 1, 1)
    assert factor_devices(16) == (1, 4, 4)
    for n in (1, 2, 4, 8, 16, 64):
        d, f, t = factor_devices(n)
        assert d * f * t == n


def test_forward_shapes_and_loss():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab)
    logits = forward(cfg, params, tokens)
    assert logits.shape == (2, 16, cfg.vocab)
    assert logits.dtype == jnp.float32
    loss = loss_fn(cfg, params, tokens)
    assert np.isfinite(float(loss))
    # fresh init should be near uniform
    assert float(loss) == pytest.approx(np.log(cfg.vocab), rel=0.2)


def test_train_step_learns():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    train_step, init_opt = make_train_step(cfg)
    step = jax.jit(train_step)
    opt_state = init_opt(params)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing one batch


def test_sharded_train_step_8dev():
    """Full dp/fsdp/tp sharded training step on the virtual 8-device mesh."""
    cfg = LlamaConfig.tiny()
    mesh = make_mesh()  # 8 cpu devices -> (1, 2, 4)
    assert mesh.devices.size == 8
    params = init_params(cfg, jax.random.key(0))
    specs = param_specs(cfg)
    params = shard_params(params, specs, mesh)
    train_step, init_opt = make_train_step(cfg)
    opt_state = init_opt(params)
    from jax.sharding import NamedSharding, PartitionSpec as P
    tok_sharding = NamedSharding(mesh, P("data", None))
    step = jax.jit(train_step)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab),
        tok_sharding)
    params2, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    # params keep their sharding through the step
    wq = params2["layers"]["wq"]
    assert wq.sharding.spec == specs["layers"]["wq"]


def test_ring_attention_matches_full():
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    B, S, H, hd = 2, 32, 4, 16
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(k1, (B, S, H, hd), dtype=jnp.float32)
    k = jax.random.normal(k2, (B, S, H, hd), dtype=jnp.float32)
    v = jax.random.normal(k3, (B, S, H, hd), dtype=jnp.float32)

    out_ring = ring_attention(q, k, v, mesh, axis="sp", causal=True)

    # dense causal reference
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    mask = jnp.tril(jnp.ones((S, S), dtype=bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)

    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_non_causal():
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, hd = 1, 64, 2, 8
    k1, k2, k3 = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(k1, (B, S, H, hd), dtype=jnp.float32)
    k = jax.random.normal(k2, (B, S, H, hd), dtype=jnp.float32)
    v = jax.random.normal(k3, (B, S, H, hd), dtype=jnp.float32)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=False)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_seq_parallel_forward_matches_dense():
    """Long-context mode: ring-attention forward == dense forward."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=4)  # ring needs H == KV
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg.vocab)
    dense = forward(cfg, params, tokens)

    devs = np.asarray(jax.devices()[:4]).reshape(1, 4)
    mesh = Mesh(devs, ("data", "sp"))
    tok_sp = jax.device_put(tokens, NamedSharding(mesh, P("data", "sp")))
    sp = jax.jit(lambda p, t: forward(cfg, p, t, mesh=mesh, sp_axis="sp"))(
        params, tok_sp)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=3e-2, atol=3e-2)  # bf16 tolerance


def test_seq_parallel_train_step_runs():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=4)
    devs = np.asarray(jax.devices()[:4]).reshape(1, 4)
    mesh = Mesh(devs, ("data", "sp"))
    params = init_params(cfg, jax.random.key(0))
    train_step, init_opt = make_train_step(cfg, mesh=mesh, sp_axis="sp")
    opt_state = init_opt(params)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (2, 65), 0, cfg.vocab),
        NamedSharding(mesh, P("data", None)))
    params, opt_state, loss = jax.jit(train_step)(params, opt_state, tokens)
    assert np.isfinite(float(loss))


def test_resnet_forward_and_pmap_dp():
    from deepflow_tpu.models import resnet
    cfg = resnet.ResNetConfig.tiny()
    params = resnet.init_params(cfg, jax.random.key(0))
    images = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    logits = resnet.forward(cfg, params, images)
    assert logits.shape == (2, cfg.num_classes)
    assert np.isfinite(np.asarray(logits)).all()

    # DP across all 8 virtual devices: pmean over the 'dp' ring
    n = jax.device_count()
    step = resnet.make_pmap_train_step(cfg, lr=0.01)
    rep = jax.device_put_replicated(params, jax.devices())
    imgs = jax.random.normal(jax.random.key(2), (n, 2, 32, 32, 3))
    labels = jax.random.randint(jax.random.key(3), (n, 2), 0,
                                cfg.num_classes)
    rep, loss = step(rep, imgs, labels)
    losses = np.asarray(loss)
    assert np.isfinite(losses).all()
    # pmean makes every replica agree
    np.testing.assert_allclose(losses, losses[0], rtol=1e-5)


def test_ring_attention_gqa_unrepeated_kv():
    """GQA ring path: KV-head blocks rotate; result matches dense GQA."""
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    B, S, H, KV, hd = 2, 32, 8, 2, 16
    k1, k2, k3 = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(k1, (B, S, H, hd), dtype=jnp.float32)
    k = jax.random.normal(k2, (B, S, KV, hd), dtype=jnp.float32)
    v = jax.random.normal(k3, (B, S, KV, hd), dtype=jnp.float32)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=True)

    kr = jnp.repeat(k, H // KV, axis=2)
    vr = jnp.repeat(v, H // KV, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr) / np.sqrt(hd)
    mask = jnp.tril(jnp.ones((S, S), dtype=bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), vr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_parallel_matches_sequential():
    from jax.sharding import Mesh
    from deepflow_tpu.parallel.pipeline import pipeline_forward
    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("pp",))
    L, D, B = 8, 16, 8  # 8 layers -> 2 per stage
    key = jax.random.key(0)
    w = jax.random.normal(key, (L, D, D), dtype=jnp.float32) * 0.3

    def stage_fn(stage_w, x):  # apply this stage's layers in order
        def body(h, wi):
            return jnp.tanh(h @ wi), None
        h, _ = jax.lax.scan(body, x, stage_w)
        return h

    x = jax.random.normal(jax.random.key(1), (B, D), dtype=jnp.float32)
    out = pipeline_forward(w, x, stage_fn, mesh, axis="pp", n_micro=4)

    ref = x
    for i in range(L):
        ref = jnp.tanh(ref @ w[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_moe_expert_parallel_matches_dense():
    from jax.sharding import Mesh
    from deepflow_tpu.models.moe import (
        init_moe_params, moe_ffn, moe_ffn_dense)
    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("ep",))
    params = init_moe_params(jax.random.key(0), d_model=32, d_ff=64,
                             n_experts=8, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(1), (24, 32), dtype=jnp.float32)
    dense = moe_ffn_dense(params, x)
    ep = moe_ffn(params, x, mesh, axis="ep")
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)
    # the fixture routes tokens onto every ep shard (2 experts/shard on 4
    # devices), so each device's non-zero path is exercised
    logits = x @ params["router"]
    shards = np.unique(np.argmax(np.asarray(logits), -1) // 2)
    assert set(shards.tolist()) == {0, 1, 2, 3}


def test_pipeline_layer_divisibility_checked():
    from jax.sharding import Mesh
    from deepflow_tpu.parallel.pipeline import pipeline_forward
    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("pp",))
    w = jnp.zeros((7, 4, 4))  # 7 layers on 4 stages: clear error
    with pytest.raises(AssertionError, match="divide by pp"):
        pipeline_forward(w, jnp.zeros((4, 4)), lambda p, x: x, mesh,
                         n_micro=2)
