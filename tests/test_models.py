import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepflow_tpu.models.llama import (
    LlamaConfig, forward, init_params, loss_fn, make_train_step, param_specs)
from deepflow_tpu.parallel import make_mesh, ring_attention, shard_params
from deepflow_tpu.parallel.mesh import factor_devices, named_sharding_tree


def test_factor_devices():
    assert factor_devices(8) == (1, 2, 4)
    assert factor_devices(1) == (1, 1, 1)
    assert factor_devices(16) == (1, 4, 4)
    for n in (1, 2, 4, 8, 16, 64):
        d, f, t = factor_devices(n)
        assert d * f * t == n


def test_forward_shapes_and_loss():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab)
    logits = forward(cfg, params, tokens)
    assert logits.shape == (2, 16, cfg.vocab)
    assert logits.dtype == jnp.float32
    loss = loss_fn(cfg, params, tokens)
    assert np.isfinite(float(loss))
    # fresh init should be near uniform
    assert float(loss) == pytest.approx(np.log(cfg.vocab), rel=0.2)


def test_train_step_learns():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.key(0))
    train_step, init_opt = make_train_step(cfg)
    step = jax.jit(train_step)
    opt_state = init_opt(params)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing one batch


def test_sharded_train_step_8dev():
    """Full dp/fsdp/tp sharded training step on the virtual 8-device mesh."""
    cfg = LlamaConfig.tiny()
    mesh = make_mesh()  # 8 cpu devices -> (1, 2, 4)
    assert mesh.devices.size == 8
    params = init_params(cfg, jax.random.key(0))
    specs = param_specs(cfg)
    params = shard_params(params, specs, mesh)
    train_step, init_opt = make_train_step(cfg)
    opt_state = init_opt(params)
    from jax.sharding import NamedSharding, PartitionSpec as P
    tok_sharding = NamedSharding(mesh, P("data", None))
    step = jax.jit(train_step)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab),
        tok_sharding)
    params2, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    # params keep their sharding through the step
    wq = params2["layers"]["wq"]
    assert wq.sharding.spec == specs["layers"]["wq"]


def test_ring_attention_matches_full():
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    B, S, H, hd = 2, 32, 4, 16
    k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(k1, (B, S, H, hd), dtype=jnp.float32)
    k = jax.random.normal(k2, (B, S, H, hd), dtype=jnp.float32)
    v = jax.random.normal(k3, (B, S, H, hd), dtype=jnp.float32)

    out_ring = ring_attention(q, k, v, mesh, axis="sp", causal=True)

    # dense causal reference
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    mask = jnp.tril(jnp.ones((S, S), dtype=bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)

    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_non_causal():
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, hd = 1, 64, 2, 8
    k1, k2, k3 = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(k1, (B, S, H, hd), dtype=jnp.float32)
    k = jax.random.normal(k2, (B, S, H, hd), dtype=jnp.float32)
    v = jax.random.normal(k3, (B, S, H, hd), dtype=jnp.float32)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=False)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
