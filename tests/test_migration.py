"""Store schema migration, crash-safe save, TTL janitor.

Reference analog: ingester/ckissu/ckissu.go:433 (versioned boot-time DDL
upgrades) + ClickHouse table TTLs. VERDICT round-1 missing #7.
"""

import json
import os
import shutil
import time

import numpy as np
import pytest

from deepflow_tpu.store import migration
from deepflow_tpu.store.db import Database
from deepflow_tpu.store.table import ColumnarTable, ColumnSpec


def _mk_table(cols):
    return ColumnarTable("flow_log.l4_flow_log", cols, chunk_rows=4)


def test_v1_dir_loads_into_v2_schema(tmp_path, monkeypatch):
    """A v1-format dir (renamed + retyped + missing columns) loads into the
    v2 schema through the migration chain."""
    # v1 table: column 'latency' (u32) that v2 calls 'rtt' (u64)
    v1 = ColumnarTable("t.demo", [ColumnSpec("time", "u64"),
                                  ColumnSpec("latency", "u32")],
                       chunk_rows=4)
    v1.append_columns({"time": [1, 2], "latency": [10, 20]})
    v1.flush()
    d = str(tmp_path / "t" / "demo")
    v1.save(d)
    # no manifest -> read as v1
    assert migration.read_manifest_version(str(tmp_path)) == 1

    monkeypatch.setitem(migration.MIGRATIONS, 1, [
        migration.Rename("t.demo", "latency", "rtt"),
        migration.Retype("t.demo", "rtt", np.uint64),
    ])
    v2 = ColumnarTable("t.demo", [ColumnSpec("time", "u64"),
                                  ColumnSpec("rtt", "u64"),
                                  ColumnSpec("added", "str")],
                       chunk_rows=4)
    v2.load(d, from_version=1)
    out = v2.column_concat(["time", "rtt", "added"])
    assert out["rtt"].tolist() == [10, 20]
    assert out["rtt"].dtype == np.uint64
    assert out["added"].tolist() == [0, 0]  # additive backfill


def test_manifest_written_and_version_gate(tmp_path):
    db = Database(data_dir=str(tmp_path))
    db.table("flow_log.l4_flow_log").append_rows(
        [{"time": 1, "flow_id": 7}])
    db.flush()
    db.save()
    mf = json.load(open(tmp_path / "MANIFEST.json"))
    assert mf["schema_version"] == migration.SCHEMA_VERSION

    # a FUTURE version must refuse to load (downgrade-unsafe)
    json.dump({"schema_version": migration.SCHEMA_VERSION + 5},
              open(tmp_path / "MANIFEST.json", "w"))
    db2 = Database(data_dir=str(tmp_path))
    with pytest.raises(RuntimeError):
        db2.load()


def test_crash_during_save_keeps_old_state(tmp_path):
    """A kill mid-save leaves either old or new state loadable — never a
    half-written directory."""
    cols = [ColumnSpec("time", "u64"), ColumnSpec("v", "u32")]
    d = str(tmp_path / "t")
    t = ColumnarTable("t", cols, chunk_rows=2)
    t.append_columns({"time": [1, 2], "v": [1, 2]})
    t.flush()
    t.save(d)

    # crash scenario A: staging half-written, swap never happened
    staging = d + ".staging"
    os.makedirs(staging)
    open(os.path.join(staging, "chunk_000000.npz"), "wb").write(b"garbage")
    t2 = ColumnarTable("t", cols, chunk_rows=2)
    t2.load(d)
    assert t2.column_concat(["time"])["time"].tolist() == [1, 2]
    assert not os.path.isdir(staging)  # staging never trusted, removed

    # crash scenario B: old renamed away, new dir never moved in
    t.save(d)  # healthy state again
    os.rename(d, d + ".old")
    t3 = ColumnarTable("t", cols, chunk_rows=2)
    t3.load(d)
    assert t3.column_concat(["time"])["time"].tolist() == [1, 2]
    assert os.path.isdir(d) and not os.path.isdir(d + ".old")

    # crash scenario C: new dir moved in but .old not yet removed
    t.save(d)
    shutil.copytree(d, d + ".old")
    # dir has the _complete marker -> it wins, .old cleaned
    t4 = ColumnarTable("t", cols, chunk_rows=2)
    t4.load(d)
    assert t4.column_concat(["time"])["time"].tolist() == [1, 2]
    assert not os.path.isdir(d + ".old")


def test_save_load_roundtrip_through_database(tmp_path):
    db = Database(data_dir=str(tmp_path))
    db.table("flow_log.l4_flow_log").append_rows(
        [{"time": 5, "flow_id": 9, "ip_src": "1.2.3.4"}])
    db.flush()
    db.save()
    db2 = Database(data_dir=str(tmp_path))
    db2.load()
    t = db2.table("flow_log.l4_flow_log")
    out = t.column_concat(["flow_id"])
    assert out["flow_id"].tolist() == [9]


def test_janitor_trims_by_ttl():
    from deepflow_tpu.server.janitor import Janitor
    db = Database()
    t = db.table("flow_log.l4_flow_log")
    now = time.time()
    old_ns = int((now - 10 * 86400) * 1e9)
    new_ns = int(now * 1e9)
    t.append_rows([{"time": old_ns, "flow_id": 1}] * 4)
    t.flush()  # sealed chunk of old rows
    t.append_rows([{"time": new_ns, "flow_id": 2}] * 2)
    t.flush()
    j = Janitor(db)
    trimmed = j.sweep(now_s=now)
    assert trimmed == 4
    assert len(t) == 2
    assert j.stats["rows_trimmed"] == 4
    # drops are visible, not silent
    assert j.stats["sweeps"] == 1
