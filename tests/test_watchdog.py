import subprocess
import sys
import time

from deepflow_tpu.agent import watchdog


def test_watchdog_restarts_crashing_child(monkeypatch):
    calls = []

    class FakeChild:
        def __init__(self, code):
            self._code = code

        def wait(self):
            return self._code

        def poll(self):
            return self._code

    codes = iter([1, 1, 0])  # crash twice, then clean exit

    def fake_popen(cmd):
        calls.append(cmd)
        return FakeChild(next(codes))

    monkeypatch.setattr(watchdog.subprocess, "Popen", fake_popen)
    monkeypatch.setattr(watchdog.time, "sleep", lambda s: None)
    rc = watchdog.run(["--standalone"], max_restarts=5, backoff_s=0.01)
    assert rc == 0
    assert len(calls) == 3
    assert calls[0][-1] == "--standalone"


def test_watchdog_gives_up(monkeypatch):
    class FakeChild:
        def wait(self):
            return 7

        def poll(self):
            return 7

    monkeypatch.setattr(watchdog.subprocess, "Popen",
                        lambda cmd: FakeChild())
    monkeypatch.setattr(watchdog.time, "sleep", lambda s: None)
    rc = watchdog.run([], max_restarts=2, backoff_s=0.01)
    assert rc == 1
