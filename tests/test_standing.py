"""Standing queries: incremental maintenance byte-identity (ISSUE 18).

The contract under test mirrors the query-parallel suite: every result
a standing query publishes must be byte-identical to a from-scratch
``engine.execute`` of the same windowed SQL — across window slides
(bucket expiry), late/out-of-order arrivals into in-window buckets,
and flushes/compactions racing the refresher mid-fold. On top of that,
the push surface guarantees exactly-once per (subscriber, generation)
and a conserved ``query.standing`` hop ledger.
"""

import threading
import time

import pytest

from deepflow_tpu.query import engine
from deepflow_tpu.query import standing as standing_mod
from deepflow_tpu.query.cache import QueryCache, change_token
from deepflow_tpu.query.standing import StandingQueryRegistry
from deepflow_tpu.store import Database
from deepflow_tpu.telemetry import Telemetry

_ROW = {"ip_src": "1.1.1.1", "ip_dst": "2.2.2.2", "server_port": 80,
        "protocol": 1, "host": "h1"}

_SQL = ("SELECT ip_src, Sum(byte_tx) AS b, Count() AS c FROM t "
        "GROUP BY ip_src ORDER BY ip_src")


@pytest.fixture(autouse=True)
def _fast_refresher(monkeypatch):
    # the production debounce (2Hz ceiling) and duty-cycle budget would
    # make every test here spend most of its wall time sleeping; the
    # logic under test is identical at any cadence
    monkeypatch.setattr(standing_mod, "MIN_GAP_S", 0.02)
    monkeypatch.setattr(standing_mod, "REFRESH_BUDGET", 0.5)


def _registry(db, telemetry=None):
    return StandingQueryRegistry(db, QueryCache(),
                                 telemetry=telemetry).start()


def _batch(t_start, n, src_mod=3, byte0=0):
    return [dict(_ROW, time=t_start + i, byte_tx=byte0 + i,
                 packet_tx=1, ip_src=f"10.0.0.{i % src_mod}")
            for i in range(n)]


def _wait_gen(sq, gen, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sq.gen > gen:
            return sq.gen
        time.sleep(0.01)
    raise AssertionError(f"gen never advanced past {gen}")


def _assert_identical(reg, sq, table):
    _brange, wsel = reg._window(sq)
    want = engine.execute(table, wsel)
    with sq.lock:
        got = (list(sq.columns), [list(r) for r in sq.rows])
    assert got == (want.columns, want.values)


def test_window_slide_byte_identity(tmp_path):
    """A 5m window over a growing table: every slide (new bucket enters,
    oldest expires) must stay byte-identical to a from-scratch execute
    of the windowed SQL — expiry drops bucket partials, it must never
    drop or double rows."""
    db = Database(data_dir=str(tmp_path), storage=True)
    t = db.table("flow_metrics.network.1s")
    t0 = 6000  # bucket-aligned (6000 = 100 * 60)
    t.append_rows(_batch(t0, 300))  # buckets 100..104
    reg = _registry(db)
    try:
        reg.register(_SQL, name="w", table=t.name, window_s=300.0)
        sq = reg.get("w")
        assert sq.gen == 1
        _assert_identical(reg, sq, t)
        # slide the window 6 times: each append lands a NEW newest
        # bucket, pushing the oldest one out of the 5-bucket window
        for k in range(6):
            gen = sq.gen
            # byte0 offset keeps the new bucket's aggregates distinct
            # from the expiring one's — identical content would make
            # the slide a (correct) no-op and no generation would move
            t.append_rows(_batch(t0 + 300 + k * 60, 60,
                                 byte0=1000 + k * 7))
            _wait_gen(sq, gen)
            _assert_identical(reg, sq, t)
        assert sq.counters["incremental"] >= 1
    finally:
        reg.stop()


def test_late_out_of_order_rows(tmp_path):
    """Late arrivals into an OLDER in-window bucket re-dirty exactly
    that bucket; rows older than the window must not resurrect it."""
    db = Database(data_dir=str(tmp_path), storage=True)
    t = db.table("flow_metrics.network.1s")
    t0 = 6000
    t.append_rows(_batch(t0, 600))  # buckets 100..109
    reg = _registry(db)
    try:
        reg.register(_SQL, name="w", table=t.name, window_s=300.0)
        sq = reg.get("w")
        # late rows into the OLDEST still-in-window bucket, descending
        gen = sq.gen
        late = _batch(t0 + 300, 40, byte0=999)
        t.append_rows(list(reversed(late)))
        _wait_gen(sq, gen)
        _assert_identical(reg, sq, t)
        # rows below the window: the result must be the one the window
        # defines — identical to from-scratch, which excludes them
        with sq.lock:
            before = [list(r) for r in sq.rows]
        def _visits():
            return sq.counters["refreshes"] + sq.counters["skipped"]
        v0 = _visits()
        t.append_rows(_batch(t0, 40, byte0=555))
        # the dirty mark fires either way, but the RESULT must not move
        # (no gen bump) — wait for the refresher to visit the query
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and _visits() == v0:
            time.sleep(0.01)
        assert _visits() > v0
        _assert_identical(reg, sq, t)
        with sq.lock:
            assert [list(r) for r in sq.rows] == before
    finally:
        reg.stop()


def test_flush_compaction_mid_fold(tmp_path):
    """Flushes swap RAM chunks for mmap'd segments underneath the
    refresher (the PR 10 race, aimed at the standing fold): with
    verify=True every refresh self-checks against a from-scratch
    execute at the same token, so one churn loop proves the fold never
    reads a half-swapped table."""
    db = Database(data_dir=str(tmp_path), storage=True)
    t = db.table("flow_metrics.network.1s")
    t.append_rows(_batch(6000, 600))
    reg = _registry(db)
    try:
        reg.register(_SQL, name="r", table=t.name, verify=True)
        sq = reg.get("r")
        stop = threading.Event()
        errs: list = []

        def _churn():
            try:
                k = 0
                while not stop.is_set():
                    t.append_rows(_batch(8000 + k * 50, 50, byte0=k))
                    db.flush_to_tier()
                    k += 1
                    time.sleep(0.005)
            except Exception as e:
                errs.append(e)

        th = threading.Thread(target=_churn)
        th.start()
        try:
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline and sq.gen < 8:
                time.sleep(0.02)
        finally:
            stop.set()
            th.join(timeout=10)
        assert not errs
        assert sq.gen >= 8, "refresher starved during churn"
        assert sq.counters["verify_failures"] == 0
        # quiesce: the refresher has folded up to the table's current
        # change token, so the maintained rows equal a fresh execute
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and sq.token != change_token(t):
            time.sleep(0.05)
        assert sq.token == change_token(t), "refresher never caught up"
        _assert_identical(reg, sq, t)
        assert sq.counters["refreshes"] >= 8
    finally:
        reg.stop()


def test_exactly_once_delivery_and_ledger(tmp_path):
    """Two subscribers each see every generation exactly once and in
    order; after they detach, the query.standing hop ledger conserves
    with nothing left in flight."""
    db = Database(data_dir=str(tmp_path), storage=True)
    t = db.table("flow_metrics.network.1s")
    t.append_rows(_batch(6000, 120))
    tel = Telemetry(component="server", enabled=True)
    reg = _registry(db, telemetry=tel)
    try:
        reg.register(_SQL, name="q", table=t.name)
        sq = reg.get("q")
        subs = [reg.subscribe(["q"])["subscriber"] for _ in range(2)]
        seen = {sid: [] for sid in subs}
        for k in range(5):
            gen = sq.gen
            t.append_rows(_batch(6200 + k * 10, 10, byte0=k * 13))
            _wait_gen(sq, gen)
        final = sq.gen
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            for sid in subs:
                out = reg.poll(sid, timeout_s=0.05)
                seen[sid].extend(u["gen"] for u in out["updates"]
                                 if u["query"] == "q")
            if all(final in g for g in seen.values()):
                break
        for sid in subs:
            gens = seen[sid]
            assert gens, "subscriber saw nothing"
            assert len(gens) == len(set(gens)), f"duplicate gen: {gens}"
            assert gens == sorted(gens), f"out of order: {gens}"
            assert gens == list(range(gens[0], gens[0] + len(gens))), \
                f"generation gap: {gens}"
            assert gens[-1] == final
        for sid in subs:
            reg.unsubscribe(sid)
        led = tel.hop("query.standing").snapshot()
        assert led["emitted"] == (led["delivered"]
                                  + led["dropped_total"]
                                  + led["in_flight"])
        assert led["in_flight"] == 0
        assert led["delivered"] > 0
    finally:
        reg.stop()


def test_kill_switch_byte_identity(tmp_path, monkeypatch):
    """DF_STANDING=0 forces every refresh through the from-scratch
    path — same registry surface, identical bytes."""
    db = Database(data_dir=str(tmp_path), storage=True)
    t = db.table("flow_metrics.network.1s")
    t.append_rows(_batch(6000, 400))
    reg = _registry(db)
    try:
        reg.register(_SQL, name="inc", table=t.name, window_s=300.0)
        monkeypatch.setenv("DF_STANDING", "0")
        reg.register(_SQL, name="off", table=t.name, window_s=300.0)
        inc, off = reg.get("inc"), reg.get("off")
        assert off.counters["full"] >= 1
        assert off.counters["incremental"] == 0
        with inc.lock:
            want = [list(r) for r in inc.rows]
        with off.lock:
            assert [list(r) for r in off.rows] == want
    finally:
        reg.stop()
