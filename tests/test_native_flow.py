"""Native C++ flow pipeline: parity with the Python FlowMap, TPACKET ring,
throughput floor.

Reference analog for coverage shape: agent/src/flow_generator/flow_map.rs
tests (flow_map.rs:3413) — same traffic, asserted outputs.
"""

import socket
import threading
import time

import numpy as np
import pytest

from deepflow_tpu.agent.flow_map import FlowMap
from deepflow_tpu.agent.packet import (
    TcpFlags, build_tcp, encode_tcp_frame, encode_udp_frame)
from deepflow_tpu.proto import pb

native_flow = pytest.importorskip("deepflow_tpu.agent.native_flow")
NativeFlowMap = native_flow.NativeFlowMap

T0 = 1_700_000_000_000_000_000


def http_frames(port_src=51000):
    c, s = "10.0.0.1", "10.0.0.2"
    req = (b"GET /api/users?id=7 HTTP/1.1\r\nHost: api.example.com\r\n"
           b"traceparent: 00-4bf92f3577b34da6a3ce929d0e0e4736-"
           b"00f067aa0ba902b7-01\r\n\r\n")
    resp = b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok"
    return [
        (encode_tcp_frame(c, s, port_src, 80, TcpFlags.SYN, seq=100), T0),
        (encode_tcp_frame(s, c, 80, port_src, TcpFlags.SYN | TcpFlags.ACK,
                          seq=300, ack=101), T0 + 1_000_000),
        (encode_tcp_frame(c, s, port_src, 80, TcpFlags.ACK, seq=101,
                          ack=301), T0 + 2_000_000),
        (encode_tcp_frame(c, s, port_src, 80, TcpFlags.ACK | TcpFlags.PSH,
                          payload=req, seq=101), T0 + 3_000_000),
        (encode_tcp_frame(s, c, 80, port_src, TcpFlags.ACK | TcpFlags.PSH,
                          payload=resp, seq=301), T0 + 13_000_000),
        (encode_tcp_frame(c, s, port_src, 80, TcpFlags.FIN | TcpFlags.ACK),
         T0 + 20_000_000),
        (encode_tcp_frame(s, c, 80, port_src, TcpFlags.FIN | TcpFlags.ACK),
         T0 + 21_000_000),
    ]


def test_native_http_session_parity():
    """Same HTTP session through both engines -> same L4 + L7 output."""
    nl4, nl7 = [], []
    nfm = NativeFlowMap(on_l4_log=nl4.append, on_l7_log=nl7.append)
    nfm.inject_frames(http_frames())
    nfm.tick(T0 + 30_000_000)

    pl4, pl7 = [], []
    pfm = FlowMap(on_l4_log=pl4.append, on_l7_log=pl7.append)
    for frame, ts in http_frames():
        from deepflow_tpu.agent.packet import decode_ethernet
        pfm.inject(decode_ethernet(frame, timestamp_ns=ts))
    pfm.tick(T0 + 30_000_000)

    assert len(nl4) == len(pl4) == 1
    nf, pf = nl4[0], pl4[0]
    for attr in ("close_type", "rtt_us", "syn_count", "synack_count",
                 "l7_request", "l7_response", "art_sum_us", "art_count",
                 "l7_protocol"):
        assert getattr(nf, attr) == getattr(pf, attr), attr
    assert nf.tx.packets == pf.tx.packets
    assert nf.rx.packets == pf.rx.packets
    assert len(nl7) == len(pl7) == 1
    nr, pr = nl7[0], pl7[0]
    assert nr.request.request_type == pr.request.request_type == "GET"
    assert nr.request.trace_id == pr.request.trace_id
    assert nr.response.response_code == pr.response.response_code == 200


def test_native_udp_dns():
    """UDP DNS query/response parses through the native L7 boundary."""
    l7 = []
    nfm = NativeFlowMap(on_l7_log=l7.append)
    # DNS query for example.com, id 0x1234
    q = (b"\x12\x34\x01\x00\x00\x01\x00\x00\x00\x00\x00\x00"
         b"\x07example\x03com\x00\x00\x01\x00\x01")
    r = (b"\x12\x34\x81\x80\x00\x01\x00\x01\x00\x00\x00\x00"
         b"\x07example\x03com\x00\x00\x01\x00\x01"
         b"\xc0\x0c\x00\x01\x00\x01\x00\x00\x00\x3c\x00\x04\x5d\xb8\xd8\x22")
    nfm.inject_frames([
        (encode_udp_frame("10.0.0.1", "8.8.8.8", 53333, 53, q), T0),
        (encode_udp_frame("8.8.8.8", "10.0.0.1", 53, 53333, r),
         T0 + 5_000_000),
    ])
    nfm.flush_all()
    assert len(l7) == 1
    assert l7[0].flow.l7_protocol == pb.DNS
    assert "example.com" in l7[0].request.request_resource


def test_native_retrans_and_seq_wrap():
    l4 = []
    nfm = NativeFlowMap(on_l4_log=l4.append)
    c, s = "10.0.0.1", "10.0.0.9"
    seq = 0xFFFFFF00
    frames = []
    for i in range(6):
        frames.append((encode_tcp_frame(
            c, s, 1234, 9999, TcpFlags.ACK | TcpFlags.PSH,
            payload=b"z" * 100, seq=(seq + i * 100) & 0xFFFFFFFF), T0 + i))
    # true retransmit post-wrap
    frames.append((encode_tcp_frame(
        c, s, 1234, 9999, TcpFlags.ACK | TcpFlags.PSH, payload=b"z" * 100,
        seq=(seq + 500) & 0xFFFFFFFF), T0 + 10))
    nfm.inject_frames(frames)
    nfm.flush_all()
    assert l4[0].tx.retrans == 1


def test_native_eviction_and_stats():
    l4 = []
    nfm = NativeFlowMap(on_l4_log=l4.append, max_flows=256)
    frames = []
    for i in range(2048):
        ip = f"10.{(i >> 8) & 255}.{i & 255}.7"
        frames.append((encode_tcp_frame(ip, "10.9.9.9", 40000 + (i % 9999),
                                        80, TcpFlags.SYN), T0 + i * 1000))
    nfm.inject_frames(frames)
    st = nfm.stats
    assert st["flows_created"] == 2048
    assert st["evicted"] == 2048 - 256
    assert nfm.active_flows == 256
    assert len(l4) == 2048 - 256
    assert all(f.close_type == "forced" for f in l4)


def test_native_exclude_ports():
    nfm = NativeFlowMap()
    nfm.exclude_port(20033)
    nfm.inject_frames([
        (encode_tcp_frame("1.1.1.1", "2.2.2.2", 5555, 20033, TcpFlags.SYN),
         T0),
        (encode_tcp_frame("1.1.1.1", "2.2.2.2", 5555, 80, TcpFlags.SYN),
         T0),
    ])
    st = nfm.stats
    assert st["excluded"] == 1
    assert st["packets"] == 1


def test_native_slow_path_ipv6():
    """IPv6 frames fall back to the embedded Python map."""
    l4 = []
    nfm = NativeFlowMap(on_l4_log=l4.append)
    # minimal IPv6/TCP SYN frame
    import struct
    src = socket.inet_pton(socket.AF_INET6, "2001:db8::1")
    dst = socket.inet_pton(socket.AF_INET6, "2001:db8::2")
    tcp = struct.pack(">HHIIBBHHH", 5555, 80, 1, 0, 5 << 4,
                      int(TcpFlags.SYN), 65535, 0, 0)
    ip6 = struct.pack(">IHBB", 6 << 28, len(tcp), 6, 64) + src + dst
    frame = b"\x00" * 12 + b"\x86\xdd" + ip6 + tcp
    nfm.inject_frames([(frame, T0)])
    assert nfm.stats["slow_path"] == 1
    nfm.flush_all()
    assert len(l4) == 1
    assert l4[0].ip_src_str() == "2001:db8::1"


def test_native_throughput_floor():
    """The VERDICT target: >= 200k pps single-core on mixed replayed
    traffic (handshakes + data + 10% payload + close)."""
    frames = []
    payload = b"x" * 256
    for fl in range(500):
        c = f"10.{(fl >> 8) & 255}.{fl & 255}.2"
        s = "10.9.9.9"
        sp = 40000 + fl
        frames.append(encode_tcp_frame(c, s, sp, 8080, TcpFlags.SYN, seq=1))
        frames.append(encode_tcp_frame(s, c, 8080, sp,
                                       TcpFlags.SYN | TcpFlags.ACK,
                                       seq=1, ack=2))
        frames.append(encode_tcp_frame(c, s, sp, 8080, TcpFlags.ACK,
                                       seq=2, ack=2))
        seq = 2
        for i in range(45):
            if i % 10 == 0:
                frames.append(encode_tcp_frame(
                    c, s, sp, 8080, TcpFlags.ACK | TcpFlags.PSH,
                    payload=payload, seq=seq))
                seq += len(payload)
            else:
                frames.append(encode_tcp_frame(c, s, sp, 8080, TcpFlags.ACK,
                                               seq=seq, ack=2))
        frames.append(encode_tcp_frame(c, s, sp, 8080,
                                       TcpFlags.FIN | TcpFlags.ACK, seq=seq))
    n = len(frames)
    offsets = np.zeros(n + 1, dtype=np.uint32)
    total = 0
    for i, f in enumerate(frames):
        total += len(f)
        offsets[i + 1] = total
    data = b"".join(frames)
    ts = np.arange(T0, T0 + n, dtype=np.uint64)

    nfm = NativeFlowMap()
    t0 = time.perf_counter()
    reps = 3
    for rep in range(reps):
        nfm.inject_batch(data, offsets, ts + rep)
    dt = time.perf_counter() - t0
    pps = n * reps / dt
    assert pps > 200_000, f"{pps:,.0f} pps below floor"


def test_native_ring_live_loopback():
    """TPACKET_V3 ring captures real loopback HTTP and parses it."""
    from deepflow_tpu.agent.native_flow import NativeRing
    l4, l7 = [], []
    nfm = NativeFlowMap(on_l4_log=l4.append, on_l7_log=l7.append)
    try:
        ring = NativeRing("lo", block_size=1 << 18, block_nr=16)
    except OSError:
        pytest.skip("CAP_NET_RAW unavailable")
    try:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(8)
        port = srv.getsockname()[1]

        def server():
            for _ in range(3):
                conn, _ = srv.accept()
                conn.recv(4096)
                conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok")
                conn.close()

        threading.Thread(target=server, daemon=True).start()
        for _ in range(3):
            c = socket.socket()
            c.connect(("127.0.0.1", port))
            c.sendall(b"GET /ring HTTP/1.1\r\nHost: lo.example\r\n\r\n")
            c.recv(4096)
            c.close()
        deadline = time.time() + 5
        while time.time() < deadline and len(l7) < 3:
            nfm.ring_rx(ring, timeout_ms=200)
        nfm.tick()
        nfm.flush_all()
        flows = [f for f in l4 if f.port_dst == port]
        assert len(flows) == 3
        recs = [r for r in l7 if r.request and
                r.request.request_domain == "lo.example"]
        assert len(recs) == 3
        assert all(r.response.response_code == 200 for r in recs)
    finally:
        ring.close()
        srv.close()


def test_native_ring_ipv6_slow_path():
    """IPv6 loopback traffic captured by the ring reaches the Python slow
    path (the ring copies undecodable frames out before block release)."""
    from deepflow_tpu.agent.native_flow import NativeRing
    l4 = []
    nfm = NativeFlowMap(on_l4_log=l4.append)
    try:
        ring = NativeRing("lo", block_size=1 << 18, block_nr=16)
    except OSError:
        pytest.skip("CAP_NET_RAW unavailable")
    try:
        srv = socket.socket(socket.AF_INET6, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind(("::1", 0))
        except OSError:
            pytest.skip("no IPv6 loopback")
        srv.listen(4)
        port = srv.getsockname()[1]

        def server():
            conn, _ = srv.accept()
            conn.recv(1024)
            conn.sendall(b"pong")
            conn.close()

        threading.Thread(target=server, daemon=True).start()
        c = socket.socket(socket.AF_INET6, socket.SOCK_STREAM)
        c.connect(("::1", port))
        c.sendall(b"ping")
        c.recv(1024)
        c.close()
        deadline = time.time() + 5
        while time.time() < deadline and nfm.stats["slow_path"] == 0:
            nfm.ring_rx(ring, timeout_ms=200)
        nfm.ring_rx(ring, timeout_ms=200)
        assert nfm.stats["slow_path"] > 0
        nfm.flush_all()
        v6 = [f for f in l4 if f.port_dst == port]
        assert v6 and v6[0].ip_src_str() == "::1"
    finally:
        ring.close()
        srv.close()
