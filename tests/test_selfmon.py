"""Self-telemetry spine: hop ledger, heartbeats, deadman, health wiring.

The e2e tests are the acceptance criteria for the telemetry PR: the
frame ledger must balance across a real agent->server run, and a
stalled stage must be detected, named, and stack-snapshotted in
/v1/health AND in deepflow_system within the configured window.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from deepflow_tpu.agent.agent import Agent
from deepflow_tpu.agent.config import AgentConfig
from deepflow_tpu.server import Server
from deepflow_tpu.telemetry import (
    DeadmanDetector, HopLedger, LatencyHistogram, Telemetry)


def _get(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.loads(resp.read())


def _post(port: int, path: str, body: dict,
          token: str | None = None) -> dict:
    headers = {"Content-Type": "application/json"}
    if token:
        headers["X-DF-Token"] = token
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(), headers=headers)
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


# -- unit: histogram / ledger / registry -------------------------------------

def test_latency_histogram_quantiles():
    h = LatencyHistogram()
    for _ in range(90):
        h.observe(500_000)          # 0.5ms -> 1ms bucket
    for _ in range(10):
        h.observe(5_000_000_000)    # 5s -> 10s bucket
    s = h.snapshot()
    assert s["count"] == 100
    assert s["p50_ms"] <= 1.0
    assert s["p99_ms"] >= 1000.0


def test_hop_ledger_conservation():
    hop = HopLedger("sender")
    hop.account(emitted=10)
    hop.account(delivered=7, wait_ns=2_000_000)
    hop.account(dropped=2, reason="queue_full")
    hop.account(dropped=1, reason="send_error")
    s = hop.snapshot()
    assert s["emitted"] == 10
    assert s["delivered"] == 7
    assert s["dropped"] == {"queue_full": 2, "send_error": 1}
    assert s["in_flight"] == 0
    assert s["emitted"] == s["delivered"] + s["dropped_total"] \
        + s["in_flight"]


def test_disabled_telemetry_is_noop():
    t = Telemetry("agent", enabled=False)
    hop = t.hop("sender")
    hop.account(emitted=5, delivered=5)
    hb = t.heartbeat("stats")
    hb.beat(progress=3)
    snap = t.snapshot()
    assert snap["enabled"] is False
    assert snap["pipeline"] == []
    assert snap["stages"] == []
    assert list(t.stats_metrics()) == []
    # a detector over a disabled registry never starts its thread
    d = DeadmanDetector(t, window_s=0.1).start()
    assert d._thread is None


def test_pipeline_order_is_registration_order():
    t = Telemetry("server")
    for name in ("receiver", "decoder.METRICS", "table_write"):
        t.hop(name)
    assert [h["hop"] for h in t.pipeline_snapshot()] == \
        ["receiver", "decoder.METRICS", "table_write"]


# -- unit: deadman ----------------------------------------------------------

def test_deadman_wedge_and_recovery():
    t = Telemetry("agent")
    d = DeadmanDetector(t, window_s=0.2)
    done = threading.Event()
    release = threading.Event()

    def stalls():
        hb = t.heartbeat("tpuprobe.relay")
        hb.beat(progress=1)
        done.set()
        release.wait(5.0)   # wedged: no further beats
        hb.beat(progress=2)

    th = threading.Thread(target=stalls, daemon=True)
    th.start()
    assert done.wait(2.0)
    assert d.check_once() == []          # still inside the window
    time.sleep(0.3)
    new = d.check_once()
    assert [w["stage"] for w in new] == ["tpuprobe.relay"]
    w = new[0]
    assert w["stalled_s"] >= 0.2 and w["progress"] == 1
    # the stack snapshot points INTO the stalled thread
    assert "stalls" in w["stack"] and "release.wait" in w["stack"]
    assert t.snapshot()["wedges_total"] == 1
    # same wedge is not re-reported while it persists...
    assert d.check_once() == []
    assert len(t.snapshot()["wedges"]) == 1
    # ...and clears as soon as the stage beats again
    release.set()
    th.join(timeout=2.0)
    d.check_once()
    assert t.snapshot()["wedges"] == []


def test_deadman_respects_interval_hint():
    t = Telemetry("server")
    hb = t.heartbeat("janitor", interval_hint_s=10.0)
    hb.beat()
    d = DeadmanDetector(t, window_s=0.1)
    time.sleep(0.15)
    # a 10s-cadence stage is not wedged after 0.15s even with a tiny
    # window: the effective window is max(window, 2.5*hint)
    assert d.check_once() == []


def test_stats_metrics_shape():
    t = Telemetry("agent")
    t.hop("sender").account(emitted=3, delivered=2, dropped=1,
                            reason="queue_full", wait_ns=1_000_000)
    t.heartbeat("stats").beat(progress=4)
    by_name = {}
    for name, tags, values in t.stats_metrics():
        by_name.setdefault(name, []).append((tags, values))
    assert by_name["agent.pipeline"][0][0] == {"hop": "sender"}
    vals = by_name["agent.pipeline"][0][1]
    assert vals["emitted"] == 3.0 and vals["dropped"] == 1.0
    drop_tags = by_name["agent.pipeline.drop"][0][0]
    assert drop_tags == {"hop": "sender", "reason": "queue_full"}
    hb_tags, hb_vals = by_name["agent.heartbeat"][0]
    assert hb_tags == {"stage": "stats"} and hb_vals["progress"] == 4.0


# -- e2e: ledger conservation through a live pipeline ------------------------

@pytest.fixture
def server():
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0,
               selfstats_interval_s=0.5).start()
    yield s
    s.stop()


def test_e2e_ledger_conservation(server):
    cfg = AgentConfig()
    cfg.app_service = "selfmon-e2e"
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.profiler.sample_hz = 200.0
    cfg.profiler.emit_interval_s = 0.2
    cfg.tpuprobe.enabled = False
    cfg.stats_interval_s = 0.3
    agent = Agent(cfg).start()
    stop = threading.Event()

    def busy():
        while not stop.is_set():
            sum(i * i for i in range(2000))

    th = threading.Thread(target=busy, name="busy")
    th.start()
    time.sleep(1.2)
    stop.set()
    th.join()
    # agent-side live ledger balances BEFORE stop (in_flight may be
    # nonzero mid-run; conservation must hold at every snapshot)
    for hop in agent.telemetry.pipeline_snapshot():
        assert hop["emitted"] == hop["delivered"] \
            + hop["dropped_total"] + hop["in_flight"], hop
    agent.stop()

    assert server.wait_for_rows("profile.in_process_profile", 1)
    assert server.wait_for_rows("deepflow_system.deepflow_system", 1)

    # after quiescence every server hop must fully drain: in_flight 0
    deadline = time.time() + 10.0
    while time.time() < deadline:
        h = _get(server.query_port, "/v1/health")
        hops = {p["hop"]: p for p in h.get("pipeline", [])}
        if hops and all(p["in_flight"] == 0 for p in hops.values()):
            break
        time.sleep(0.2)
    assert hops, "no server pipeline telemetry in /v1/health"
    assert "receiver" in hops
    assert any(k.startswith("decoder.") for k in hops)
    assert "table_write" in hops
    for name, p in hops.items():
        assert p["in_flight"] == 0, f"{name} did not drain: {p}"
        assert p["emitted"] == p["delivered"] + p["dropped_total"], p
    assert hops["receiver"]["emitted"] > 0
    assert hops["table_write"]["delivered"] > 0
    # queue-wait histograms saw real traffic (the enqueue->dequeue wait
    # is observed by the decoder at dequeue time)
    assert any(p["wait"]["count"] > 0 for k, p in hops.items()
               if k.startswith("decoder."))
    assert h["ledger_imbalance"] == 0

    # server stages are beating and none is wedged
    stages = {s["stage"]: s for s in h["stages"]}
    for required in ("receiver", "janitor", "deadman", "selfstats"):
        assert required in stages, sorted(stages)
        assert stages[required]["beats"] >= 1
        assert not stages[required]["wedged"]
    assert any(s.startswith("decoder.") for s in stages)
    assert h["status"] == "ok"

    # the agent's ledger + heartbeats came back out of deepflow_system
    ag = h.get("agents_selfmon")
    assert ag, "agent selfmon rows missing from /v1/health"
    assert "sender" in ag["pipeline"]
    assert ag["pipeline"]["sender"]["emitted"] >= 1
    assert "stats" in ag["heartbeats"]
    assert ag["wedges"] == []

    # and the same rows resolve through plain DF-SQL (PromQL shares
    # this path via the deepflow_system_* narrow-table mapping)
    out = _post(server.query_port, "/v1/query/", {
        "db": "deepflow_system",
        "sql": "SELECT metric_name, Count(1) AS n FROM deepflow_system "
               "WHERE metric_name = 'agent.pipeline' GROUP BY metric_name"})
    assert out["result"]["values"], out


# -- e2e: wedge detection (the regression test from ADVICE r5) ---------------

def test_e2e_wedge_detected_named_and_stack_snapshotted(server):
    cfg = AgentConfig()
    cfg.app_service = "selfmon-wedge"
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.stats_interval_s = 0.3
    cfg.selfmon.deadman_window_s = 0.6
    cfg.selfmon.check_interval_s = 0.15
    agent = Agent(cfg).start()
    release = threading.Event()

    def fake_relay():
        # stands in for a tpuprobe source/relay thread that wedges inside
        # capture_once: beats once on entry, then not again until released
        hb = agent.telemetry.heartbeat("tpuprobe.relay")
        hb.beat(progress=1)
        release.wait(30.0)
        hb.beat(progress=2)  # recovery beat

    th = threading.Thread(target=fake_relay, name="fake-relay",
                          daemon=True)
    th.start()
    try:
        # within the window (+ shipping latency) the wedge must surface in
        # /v1/health, sourced from deepflow_system rows
        deadline = time.time() + 10.0
        h = {}
        while time.time() < deadline:
            h = _get(server.query_port, "/v1/health")
            if h.get("status") == "degraded":
                break
            time.sleep(0.2)
        assert h.get("status") == "degraded", h.get("status")
        assert "agent:tpuprobe.relay" in h["wedged_stages"]
        wedges = {w["stage"]: w
                  for w in h["agents_selfmon"]["wedges"]}
        assert "tpuprobe.relay" in wedges
        w = wedges["tpuprobe.relay"]
        assert w.get("wedged") == 1.0
        assert w.get("stalled_s", 0) >= 0.6
        # the stack names the wedged frame, not just the stage
        assert "fake_relay" in w["stack"]
        assert "release.wait" in w["stack"]
        hb = h["agents_selfmon"]["heartbeats"]["tpuprobe.relay"]
        assert hb["wedged"] == 1.0

        # raw rows landed in deepflow_system.deepflow_system too (the
        # PromQL/alerting surface)
        t = server.db.table("deepflow_system.deepflow_system")
        sid = t.dicts["metric_name"].lookup("agent.deadman")
        assert sid is not None, "no agent.deadman rows shipped"
    finally:
        release.set()
        th.join(timeout=2.0)

    # recovery: the stage beat again, so the next deadman scan clears
    # the verdict from the live registry...
    agent.deadman.check_once()
    assert agent.telemetry.snapshot()["wedges"] == []
    # ...and the final stats flush in stop() ships wedged=0 heartbeat
    # rows, so /v1/health returns to ok
    agent.stop()
    deadline = time.time() + 10.0
    h = {}
    while time.time() < deadline:
        h = _get(server.query_port, "/v1/health")
        if h["status"] == "ok":
            break
        time.sleep(0.2)
    assert h.get("status") == "ok", h.get("wedged_stages")


# -- satellite: control-plane token gating -----------------------------------

def test_token_gates_repo_upload_and_upgrade_exec():
    import base64
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0, sync_port=0,
               enable_controller=True, api_token="s3cret").start()
    try:
        data_b64 = base64.b64encode(b"pkg-bytes").decode()
        up = {"action": "upload", "name": "agent", "version": "v9",
              "data_b64": data_b64}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(s.query_port, "/v1/repo", up)
        assert ei.value.code == 403
        # wrong token is still 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(s.query_port, "/v1/repo", up, token="wrong")
        assert ei.value.code == 403
        # body-field token works too (CLI sends the header)
        out = _post(s.query_port, "/v1/repo", up, token="s3cret")
        assert out["uploaded"]["version"] == "v9"
        # list stays open: read-only, not part of the OTA exec path
        out = _post(s.query_port, "/v1/repo", {"action": "list"})
        assert "agent" in out["packages"]

        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(s.query_port, "/v1/agents/exec",
                  {"agent_id": 1, "cmd": "upgrade", "args": ["version=v9"]})
        assert ei.value.code == 403
        out = _post(s.query_port, "/v1/agents/exec",
                    {"agent_id": 1, "cmd": "upgrade",
                     "args": ["version=v9"], "token": "s3cret"})
        assert "result_id" in out
        # non-upgrade exec commands stay open (read-only diagnostics)
        out = _post(s.query_port, "/v1/agents/exec",
                    {"agent_id": 1, "cmd": "status"})
        assert "result_id" in out
    finally:
        s.stop()
