"""HA / horizontal scale-out: leader election, analyzer rebalance,
exporter disk spool.

Reference analogs: controller/election/election.go:175, controller/monitor
(analyzer rebalance), ingester exporter durability. VERDICT round-1
missing #6 + weak #9.
"""

import http.server
import json
import os
import threading
import time

import pytest

from deepflow_tpu.server.election import LeaderElection


def test_single_candidate_wins_and_renews(tmp_path):
    lease = str(tmp_path / "lease")
    el = LeaderElection(lease, holder="a")
    assert el.try_acquire() is True
    assert el.is_leader and el.token == 1
    assert el.try_acquire() is True       # renewal keeps the token
    assert el.token == 1
    assert el.stats["renewals"] == 1


def test_second_candidate_defers_then_takes_over(tmp_path):
    lease = str(tmp_path / "lease")
    a = LeaderElection(lease, holder="a")
    b = LeaderElection(lease, holder="b")
    assert a.try_acquire() is True
    assert b.try_acquire() is False       # kernel lock held by a
    a.resign()                             # a dies / releases
    assert b.try_acquire() is True
    assert b.token == 2                    # fencing token advanced
    # a comes back: lock is held, steps down stays down
    assert a.try_acquire() is False
    assert a.is_leader is False
    # exactly one leader at every instant (flock is kernel-enforced)
    assert b.is_leader


def test_sigkilled_leader_flock_released_and_fencing_advances(tmp_path):
    """A SIGKILLed leader never resigns — but flock is kernel-owned, so
    the lock drops with the process and a follower acquires within one
    renew interval, with a strictly larger fencing token (a zombie
    holder's writes stay fenceable)."""
    import signal
    import subprocess
    import sys

    lease = str(tmp_path / "lease")
    child = subprocess.Popen(
        [sys.executable, "-c",
         "import sys, time\n"
         "from deepflow_tpu.server.election import LeaderElection\n"
         "el = LeaderElection(sys.argv[1], holder='child')\n"
         "assert el.try_acquire()\n"
         "print(el.token, flush=True)\n"
         "time.sleep(60)\n",
         lease],
        stdout=subprocess.PIPE, text=True)
    try:
        child_token = int(child.stdout.readline().strip())
        assert child_token >= 1
        follower = LeaderElection(lease, holder="follower",
                                  renew_interval_s=0.2)
        assert follower.try_acquire() is False    # kernel lock held
        child.send_signal(signal.SIGKILL)          # no resign, no drain
        child.wait(timeout=10)
        deadline = time.time() + follower.renew_interval_s + 5.0
        while time.time() < deadline and not follower.try_acquire():
            time.sleep(0.05)
        assert follower.is_leader
        assert follower.token > child_token        # strictly increases
    finally:
        if child.poll() is None:
            child.kill()
        child.stdout.close()


def test_graceful_resign_hands_over(tmp_path):
    lease = str(tmp_path / "lease")
    a = LeaderElection(lease, holder="a", ttl_s=30.0)
    b = LeaderElection(lease, holder="b", ttl_s=30.0)
    assert a.try_acquire() is True
    a.resign()
    assert b.try_acquire() is True        # no TTL wait needed


def test_server_singletons_follow_leadership(tmp_path):
    """Two servers, one lease: exactly one runs the singletons; the
    follower takes over when the leader resigns."""
    from deepflow_tpu.server import Server
    lease = str(tmp_path / "lease")
    s1 = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                sync_port=0, enable_controller=True,
                ha_lease_path=lease).start()
    # make s1's election fast to observe
    s2 = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                sync_port=0, enable_controller=True,
                ha_lease_path=lease).start()
    try:
        leaders = [s.election.is_leader for s in (s1, s2)]
        assert sorted(leaders) == [False, True]
        leader, follower = (s1, s2) if s1.election.is_leader else (s2, s1)
        assert leader.rollup.running() and leader.janitor.running()
        assert leader.controller.running()
        assert not follower.rollup.running()
        assert not follower.controller.running()
        # failover
        leader.election.renew_interval_s = 0.2
        follower.election.renew_interval_s = 0.2
        leader.election.resign()
        deadline = time.time() + 10
        while time.time() < deadline and not follower.election.is_leader:
            follower.election.try_acquire()
            time.sleep(0.1)
        assert follower.election.is_leader
        deadline = time.time() + 5
        while time.time() < deadline and not follower.rollup.running():
            time.sleep(0.05)
        assert follower.rollup.running() and follower.controller.running()
    finally:
        s1.stop()
        s2.stop()


def test_analyzer_rendezvous_assignment():
    """Per-agent preference orders spread the fleet and stay mostly stable
    when a node joins."""
    from deepflow_tpu.server.controller import Controller
    from deepflow_tpu.server.platform_info import PlatformInfoTable
    ctrl = Controller(PlatformInfoTable())
    ctrl.set_analyzers(["10.0.0.1:20033", "10.0.0.2:20033",
                        "10.0.0.3:20033"])
    first = {}
    counts = {}
    for agent_id in range(300):
        order = ctrl.assign_analyzers(agent_id)
        assert sorted(order) == sorted(ctrl.analyzers())
        first[agent_id] = order[0]
        counts[order[0]] = counts.get(order[0], 0) + 1
    # spread: no analyzer owns everything
    assert all(40 <= c <= 160 for c in counts.values()), counts
    # minimal churn: adding a node moves only the agents it claims
    ctrl.set_analyzers(["10.0.0.1:20033", "10.0.0.2:20033",
                        "10.0.0.3:20033", "10.0.0.4:20033"])
    moved = sum(1 for a in range(300)
                if ctrl.assign_analyzers(a)[0] != first[a])
    assert moved < 150  # rendezvous: ~1/4 expected, never a full reshuffle
    for a in range(300):
        new_first = ctrl.assign_analyzers(a)[0]
        if new_first != first[a]:
            assert new_first == "10.0.0.4:20033"


def test_exporter_spool_and_replay(tmp_path):
    """Exhausted retries spool to disk and replay when the destination
    recovers; nothing silently drops."""
    from deepflow_tpu.server.exporters import JsonLinesExporter

    received = []
    fail = {"on": True}

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            if fail["on"]:
                self.send_response(503)
                self.end_headers()
                return
            received.append(body)
            self.send_response(200)
            self.end_headers()

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    exp = JsonLinesExporter(
        f"http://127.0.0.1:{srv.server_port}/ingest",
        spool_dir=str(tmp_path / "spool"))
    exp.flush_interval_s = 0.2
    exp.max_retries = 0
    exp.start()
    try:
        exp.feed("flow_log.l4_flow_log", [{"flow_id": 1}, {"flow_id": 2}])
        deadline = time.time() + 10
        while time.time() < deadline and exp.stats["spooled"] < 2:
            time.sleep(0.05)
        assert exp.stats["spooled"] == 2
        assert exp.stats["dropped"] == 0
        assert os.listdir(tmp_path / "spool")
        # destination recovers: next successful ship triggers replay
        fail["on"] = False
        exp.feed("flow_log.l4_flow_log", [{"flow_id": 3}])
        deadline = time.time() + 10
        while time.time() < deadline and exp.stats["replayed"] < 2:
            time.sleep(0.05)
        assert exp.stats["replayed"] == 2
        assert not [f for f in os.listdir(tmp_path / "spool")
                    if f.endswith(".spool")]
        import gzip
        flows = set()
        for body in received:
            for line in gzip.decompress(body).decode().splitlines():
                flows.add(json.loads(line).get("flow_id"))
        assert flows == {1, 2, 3}
    finally:
        exp.stop()
        srv.shutdown()


def test_analyzer_assignment_revert(tmp_path):
    """Clearing the analyzer list reverts agents to configured servers."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from deepflow_tpu.server import Server
    from deepflow_tpu.agent.agent import Agent
    from deepflow_tpu.agent.config import AgentConfig

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0,
                    sync_port=0, enable_controller=True).start()
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.controller = f"127.0.0.1:{server.controller.port}"
    cfg.sync_interval_s = 0.2
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    agent = Agent(cfg).start()
    try:
        configured = list(agent.sender.servers)
        server.controller.set_analyzers(["10.9.9.9:20033"])
        deadline = time.time() + 10
        while time.time() < deadline and \
                agent.sender.servers == configured:
            time.sleep(0.1)
        assert agent.sender.servers == [("10.9.9.9", 20033)]
        server.controller.set_analyzers([])   # decommission the tier
        deadline = time.time() + 10
        while time.time() < deadline and \
                agent.sender.servers != configured:
            time.sleep(0.1)
        assert agent.sender.servers == configured
    finally:
        agent.stop()
        server.stop()


def test_spool_survives_restart(tmp_path):
    """Batches spooled by a previous process replay after restart."""
    from deepflow_tpu.server.exporters import JsonLinesExporter
    spool = str(tmp_path / "spool")
    # process 1: destination down, batch lands in the spool
    e1 = JsonLinesExporter("http://127.0.0.1:9/none", spool_dir=spool)
    e1.flush_interval_s = 0.1
    e1.max_retries = 0
    e1.start()
    e1.feed("flow_log.l4_flow_log", [{"flow_id": 77}])
    deadline = time.time() + 10
    while time.time() < deadline and e1.stats["spooled"] < 1:
        time.sleep(0.05)
    e1.stop()
    assert [f for f in os.listdir(spool) if f.endswith(".spool")]

    # process 2 (fresh exporter, healthy destination): replays the spool
    received = []

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            received.append(self.rfile.read(n))
            self.send_response(200)
            self.end_headers()

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    e2 = JsonLinesExporter(f"http://127.0.0.1:{srv.server_port}/i",
                           spool_dir=spool)
    e2.flush_interval_s = 0.1
    e2.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and e2.stats["replayed"] < 1:
            time.sleep(0.05)
        assert e2.stats["replayed"] == 1
        assert not [f for f in os.listdir(spool) if f.endswith(".spool")]
    finally:
        e2.stop()
        srv.shutdown()


def test_spool_poison_file_quarantined(tmp_path):
    """A batch the destination deterministically rejects gets quarantined
    after bounded retries instead of blocking everything behind it."""
    from deepflow_tpu.server.exporters import JsonLinesExporter

    received = []

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            import gzip as _gz
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            if b"poison" in _gz.decompress(body):
                self.send_response(413)   # permanent rejection
            else:
                received.append(body)
                self.send_response(200)
            self.end_headers()

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    spool = str(tmp_path / "spool")
    exp = JsonLinesExporter(f"http://127.0.0.1:{srv.server_port}/i",
                            spool_dir=spool)
    exp.spool_dir = spool
    os.makedirs(spool)
    # pre-seed a poison batch followed by a good one (as a prior run would)
    import pickle
    with open(os.path.join(spool, "0001.spool"), "wb") as f:
        pickle.dump([("t", {"k": "poison"})], f)
    with open(os.path.join(spool, "0002.spool"), "wb") as f:
        pickle.dump([("t", {"k": "good"})], f)
    exp.flush_interval_s = 0.1
    exp._next_replay = 0
    exp.start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline and exp.stats["replayed"] < 1:
            exp._next_replay = 0   # bypass the 5s throttle for the test
            time.sleep(0.05)
        assert exp.stats["replayed"] == 1          # the good batch shipped
        assert exp.stats["spool_dropped"] == 1     # poison visible as drop
        assert [f for f in os.listdir(spool) if f.endswith(".bad")]
        assert not [f for f in os.listdir(spool) if f.endswith(".spool")]
    finally:
        exp.stop()
        srv.shutdown()


class _FakeLeaseApi(http.server.BaseHTTPRequestHandler):
    """coordination.k8s.io/v1 Lease with resourceVersion CAS."""
    state = {"lease": None, "rv": 0}

    def log_message(self, *a):
        pass

    def _send(self, code, obj=None):
        body = json.dumps(obj or {}).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        st = self.state
        if st["lease"] is None:
            self._send(404, {"reason": "NotFound"})
        else:
            self._send(200, st["lease"])

    def do_POST(self):
        st = self.state
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        if st["lease"] is not None:
            self._send(409, {"reason": "AlreadyExists"})
            return
        st["rv"] += 1
        body.setdefault("metadata", {})["resourceVersion"] = str(st["rv"])
        st["lease"] = body
        self._send(201, body)

    def do_PUT(self):
        st = self.state
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n))
        want = body.get("metadata", {}).get("resourceVersion", "")
        have = (st["lease"] or {}).get("metadata", {}).get(
            "resourceVersion", "")
        if st["lease"] is None or want != have:
            self._send(409, {"reason": "Conflict"})
            return
        st["rv"] += 1
        body["metadata"]["resourceVersion"] = str(st["rv"])
        st["lease"] = body
        self._send(200, body)


def test_k8s_lease_election_single_leader_and_takeover():
    """Lease-object election: CAS arbitration, expiry takeover, fencing
    transitions — against a faithful fake apiserver."""
    from deepflow_tpu.server.election import K8sLeaseElection
    _FakeLeaseApi.state = {"lease": None, "rv": 0}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _FakeLeaseApi)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_port}"
    try:
        a = K8sLeaseElection("df-leader", api_base=base, holder="a",
                             ttl_s=1.0)
        b = K8sLeaseElection("df-leader", api_base=base, holder="b",
                             ttl_s=1.0)
        assert a.try_acquire() is True        # CREATE wins
        assert b.try_acquire() is False       # fresh lease held by a
        assert a.try_acquire() is True        # renewal
        assert a.stats["renewals"] == 1
        # a stops renewing; b must observe the renewTime STABLE for a
        # full ttl by its own clock before takeover (skew-safe expiry)
        deadline = time.time() + 5
        while time.time() < deadline and not b.try_acquire():
            time.sleep(0.2)
        assert b.is_leader is True            # expiry takeover via CAS PUT
        assert b.token_fencing == 2            # transitions advanced
        assert a.try_acquire() is False       # a steps down
        assert a.stats["depositions"] == 1
        # graceful resign removes renewTime: a wins IMMEDIATELY (no ttl
        # wait — missing renewTime means expired now)
        b.resign()
        assert a.try_acquire() is True
        assert a.token_fencing == 3
    finally:
        srv.shutdown()


def test_server_accepts_k8s_lease_option(tmp_path, monkeypatch):
    """Server wires K8sLeaseElection when ha_k8s_lease is given and
    degrades to local singletons when no cluster is reachable."""
    from deepflow_tpu.server import Server
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0,
               ha_k8s_lease="df-leader").start()
    try:
        # no cluster: degraded to local singletons, still fully serving
        assert s.election is None
        assert s.rollup.running() and s.janitor.running()
    finally:
        s.stop()
