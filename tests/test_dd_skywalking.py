"""Datadog (/v0.4/traces msgpack) and SkyWalking (/v3/segments) ingest.

Reference analog: agent/src/integration_collector.rs:893 (datadog),
ingester/flow_log decoder skywalking handler.
"""

import json
import urllib.request

import pytest

from deepflow_tpu.utils import msgpack


def test_msgpack_roundtrip():
    obj = {
        "trace_id": 2 ** 63 + 5, "neg": -1234567, "small": -5,
        "f": 1.25, "name": "web.request", "ok": True, "none": None,
        "arr": list(range(20)), "bin": b"\x00\x01",
        "nested": {"k" * 40: "v" * 300},
    }
    assert msgpack.unpackb(msgpack.packb(obj)) == obj


def test_msgpack_rejects_garbage():
    with pytest.raises(msgpack.MsgpackError):
        msgpack.unpackb(b"\xc1")  # never-used type byte
    with pytest.raises(msgpack.MsgpackError):
        msgpack.unpackb(b"\xda\x00\x10abc")  # truncated str16
    with pytest.raises(msgpack.MsgpackError):
        msgpack.unpackb(msgpack.packb({"a": 1}) + b"x")  # trailing


def _dd_span(trace_id, span_id, parent=0, service="checkout",
             name="web.request", resource="/pay", error=0, code=200):
    return {
        "trace_id": trace_id, "span_id": span_id, "parent_id": parent,
        "service": service, "name": name, "resource": resource,
        "type": "web", "error": error,
        "start": 1_700_000_000_000_000_000, "duration": 25_000_000,
        "meta": {"http.method": "POST", "http.status_code": str(code),
                 "http.host": "shop.example"},
        "metrics": {"_sampling_priority_v1": 1},
    }


def test_datadog_and_skywalking_ingest():
    from deepflow_tpu.query import execute
    from deepflow_tpu.server import Server

    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        base = f"http://127.0.0.1:{server.query_port}"
        # datadog: two traces, msgpack body, PUT like dd-trace does
        body = msgpack.packb([
            [_dd_span(7, 1), _dd_span(7, 2, parent=1, name="db.query",
                                      resource="SELECT orders")],
            [_dd_span(8, 9, error=1, code=500)],
        ])
        req = urllib.request.Request(f"{base}/v0.4/traces", data=body,
                                     method="PUT")
        out = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert out == {"accepted_spans": 3}

        # skywalking: one segment with an exit + entry span pair
        seg = {
            "traceId": "sw-trace-1", "traceSegmentId": "seg-a",
            "service": "cart",
            "spans": [
                {"spanId": 0, "parentSpanId": -1,
                 "operationName": "GET:/cart", "startTime": 1700000000100,
                 "endTime": 1700000000150,
                 "tags": [{"key": "http.method", "value": "GET"},
                          {"key": "http.status_code", "value": "200"}]},
                {"spanId": 1, "parentSpanId": 0, "isError": True,
                 "operationName": "mysql/query", "startTime": 1700000000110,
                 "endTime": 1700000000140, "tags": []},
            ],
        }
        req = urllib.request.Request(f"{base}/v3/segments",
                                     data=json.dumps(seg).encode(),
                                     headers={"Content-Type":
                                              "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert out == {"accepted_spans": 2}

        t = server.db.table("flow_log.l7_flow_log")
        r = execute(t, "SELECT app_service, endpoint, response_code, "
                       "response_status, trace_id, parent_span_id "
                       "FROM l7_flow_log")
        rows = r.values if hasattr(r, "values") else r["values"]
        assert len(rows) == 5
        dd = [x for x in rows if x[0] == "checkout"]
        assert len(dd) == 3
        # u64 ids rendered as 16-hex; parentage preserved
        child = [x for x in dd if x[1] == "db.query"][0]
        assert child[4] == f"{7:016x}"
        assert child[5] == f"{1:016x}"
        err = [x for x in dd if x[3] == "server_error"]
        assert len(err) == 1 and err[0][2] == 500
        sw = [x for x in rows if x[0] == "cart"]
        assert len(sw) == 2
        assert {x[4] for x in sw} == {"sw-trace-1"}
        assert [x for x in sw if x[1] == "mysql/query"][0][5] == "seg-a-0"

        # trace view joins the datadog parent/child spans
        req = urllib.request.Request(
            f"{base}/v1/trace/Tracing",
            data=json.dumps({"trace_id": f"{7:016x}"}).encode())
        tr = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert tr["result"]["span_count"] == 2
        root = tr["result"]["spans"][0]
        assert root["children"], "child span must nest under the root"
    finally:
        server.stop()


def test_msgpack_32bit_lengths_roundtrip():
    big = {"s": "x" * 70000, "b": b"y" * 70000, "a": list(range(70000))}
    assert msgpack.unpackb(msgpack.packb(big)) == big


def test_bad_span_values_do_not_500_the_batch():
    from deepflow_tpu.server.integration import IntegrationAPI
    from deepflow_tpu.store import Database
    api = IntegrationAPI(Database())
    out = api.ingest_datadog(json.dumps([[{
        "trace_id": 5, "span_id": 6, "service": "s", "name": "n",
        "resource": "r", "start": 1, "duration": 2,
        "meta": {"http.status_code": "error"},  # non-numeric tag
    }]]).encode(), "application/json")
    assert out == {"accepted_spans": 1}
    out = api.ingest_skywalking({
        "traceId": "t", "traceSegmentId": "seg", "service": "svc",
        "spans": [{"spanId": 0, "parentSpanId": -1, "operationName": "op",
                   "startTime": 1, "endTime": 2,
                   "tags": [{"key": "status_code", "value": "OK"}]}]})
    assert out == {"accepted_spans": 1}


def test_skywalking_malformed_spans_are_isolated():
    from deepflow_tpu.server.integration import IntegrationAPI
    from deepflow_tpu.store import Database
    api = IntegrationAPI(Database())
    out = api.ingest_skywalking({
        "traceId": "t", "traceSegmentId": "seg", "service": "svc",
        "spans": [None, {"spanId": 1, "tags": None},
                  {"spanId": 2, "parentSpanId": -1,
                   "refs": [{"parentSpanId": 3}]},  # no parent segment id
                  "junk"]})
    assert out == {"accepted_spans": 2}
    rows = api.db.table("flow_log.l7_flow_log").snapshot()
    parents = []
    for ch in rows:
        if ch and len(ch.get("span_id", ())):
            d = api.db.table("flow_log.l7_flow_log").dicts["parent_span_id"]
            parents += [d.decode(int(x)) for x in ch["parent_span_id"]]
    assert "None-3" not in parents  # missing ref segment id -> empty parent


def test_put_is_scoped_to_datadog_paths():
    import urllib.error

    from deepflow_tpu.server import Server
    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.query_port}/v1/alerts",
            data=b"{}", method="PUT")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 405
    finally:
        server.stop()


def test_msgpack_nesting_depth_bounded():
    # ~2KB of nested fixarrays must raise MsgpackError (-> 400), not
    # RecursionError (-> 500)
    deep = b"\x91" * 2000 + b"\xc0"
    with pytest.raises(msgpack.MsgpackError):
        msgpack.unpackb(deep)
    # sane nesting still decodes
    ok = b"\x91" * 50 + b"\xc0"
    v = msgpack.unpackb(ok)
    for _ in range(50):
        assert isinstance(v, list) and len(v) == 1
        v = v[0]
    assert v is None


def test_msgpack_container_map_key_rejected():
    # fixmap{fixarray: nil} — unhashable key must be MsgpackError, not
    # TypeError (which the HTTP layer would 500)
    with pytest.raises(msgpack.MsgpackError):
        msgpack.unpackb(b"\x81\x90\xc0")
