"""DWARF (.eh_frame) unwinding: table building and full-stack recovery
from frame-pointer-omitted binaries.

Reference analog: agent/crates/trace-utils/src/unwind/dwarf.rs (table
build) + kernel/perf_profiler.bpf.c:1015 PROGPE(dwarf_unwind) (walk).
VERDICT round-1 §2.2: "no DWARF unwinder (FP chains; gap documented)".
"""

import ctypes
import os
import re
import shutil
import subprocess
import textwrap
import time

import numpy as np
import pytest

from deepflow_tpu import native
from deepflow_tpu.agent import ehframe

LIBC = "/lib/x86_64-linux-gnu/libc.so.6"


def test_ehframe_parse_libc():
    if not os.path.exists(LIBC):
        pytest.skip("no libc at the expected path")
    t = ehframe.load_unwind_table(LIBC)
    assert t is not None and len(t) > 1000
    assert t.n_fdes > 500
    # sorted by pc
    assert np.all(np.diff(t.pc.astype(np.int64)) >= 0)
    valid = t.cfa_reg < 2
    assert valid.mean() > 0.5  # most rows walkable
    # the x86-64 ABI norm: return address at CFA-8 (signal-restore frames
    # are the legitimate exceptions)
    assert (t.ra_off[valid] == -8).mean() > 0.99


def test_ehframe_matches_readelf_rows():
    """Row-level conformance against readelf -wF interpreted tables."""
    if not os.path.exists(LIBC) or not shutil.which("readelf"):
        pytest.skip("readelf or libc unavailable")
    out = subprocess.run(["readelf", "-wF", LIBC], capture_output=True,
                         text=True, timeout=120).stdout
    t = ehframe.load_unwind_table(LIBC)

    def our_row(loc):
        i = int(np.searchsorted(t.pc, np.uint64(loc), side="right")) - 1
        reg = {0: "rsp", 1: "rbp", 2: None}[int(t.cfa_reg[i])]
        return reg, int(t.cfa_off[i]), int(t.ra_off[i])

    checked = 0
    for blk in out.split("\n\n"):
        if "FDE" not in blk:
            continue
        lines = blk.splitlines()
        hdr = next((i for i, ln in enumerate(lines)
                    if ln.strip().startswith("LOC")), None)
        if hdr is None:
            continue
        cols = lines[hdr].split()
        for ln in lines[hdr + 1:]:
            parts = ln.split()
            if len(parts) != len(cols):
                continue
            loc = int(parts[0], 16)
            cfa = parts[cols.index("CFA")]
            ra = parts[cols.index("ra")]
            mm = re.match(r"(rsp|rbp)\+(\d+)$", cfa)
            greg, goff, gra = our_row(loc)
            if not mm:
                assert greg is None, (hex(loc), cfa, greg)
                continue
            assert (greg, goff) == (mm.group(1), int(mm.group(2))), \
                (hex(loc), cfa, greg, goff)
            if ra.startswith("c-"):
                assert gra == -int(ra[2:]), (hex(loc), ra, gra)
            checked += 1
    assert checked > 10_000, checked


# -- functional: full stacks from an FP-omitted binary -----------------------

if native.load() is None:
    pytest.skip("libdfnative.so unavailable", allow_module_level=True)


def _perf_available() -> bool:
    lib = native.load()
    from deepflow_tpu.agent.extprofiler import ExternalProfiler
    ExternalProfiler._bind(lib)
    err = ctypes.c_int32(0)
    h = lib.df_prof_open(os.getpid(), 99, 16, ctypes.byref(err))
    if not h:
        return False
    lib.df_prof_close(h)
    return True


DEEP_C = textwrap.dedent("""
    #include <stdint.h>
    volatile uint64_t sink;
    __attribute__((noinline)) uint64_t deep_leaf(uint64_t n) {
        uint64_t a = 1;
        for (uint64_t i = 1; i < n; i++) a = a * 7 + i;
        return a;
    }
    __attribute__((noinline)) uint64_t lvl3(uint64_t n) {
        uint64_t v = deep_leaf(n); sink += 3; return v;
    }
    __attribute__((noinline)) uint64_t lvl2(uint64_t n) {
        uint64_t v = lvl3(n); sink += 2; return v;
    }
    __attribute__((noinline)) uint64_t lvl1(uint64_t n) {
        uint64_t v = lvl2(n); sink += 1; return v;
    }
    int main() { for (;;) sink += lvl1(400000); }
""")


@pytest.fixture(scope="module")
def fp_omitted_binary(tmp_path_factory):
    d = tmp_path_factory.mktemp("deep")
    src = d / "deep.c"
    src.write_text(DEEP_C)
    exe = d / "deep"
    # -fomit-frame-pointer: rbp is a scratch register, FP chains break;
    # .eh_frame is still emitted (the default on amd64) for the unwinder
    subprocess.run(["gcc", "-O1", "-fomit-frame-pointer", "-fno-inline",
                    "-o", str(exe), str(src)], check=True)
    return str(exe)


@pytest.mark.skipif(not _perf_available(), reason="perf_event unavailable")
def test_dwarf_recovers_fp_omitted_stacks(fp_omitted_binary):
    """The headline: full main->lvl1->lvl2->lvl3->deep_leaf chains from a
    binary whose frame pointers are gone."""
    from deepflow_tpu.agent.extprofiler import ExternalProfiler
    proc = subprocess.Popen([fp_omitted_binary])
    try:
        time.sleep(0.2)
        batches = []
        prof = ExternalProfiler(batches.append, pid=proc.pid, hz=199,
                                window_s=0.5, dwarf=True).start()
        time.sleep(2.5)
        prof.stop()
    finally:
        proc.kill()
    assert prof.unwind_tables >= 1  # the test binary's table registered
    assert prof.dwarf_samples > 0, \
        (prof.dwarf_samples, prof.fp_samples, prof.stats.samples)
    stacks: dict[str, int] = {}
    for b in batches:
        for s in b:
            stacks[s.stack] = stacks.get(s.stack, 0) + s.count
    assert stacks
    top = max(stacks.items(), key=lambda kv: kv[1])[0]
    for fn in ("main", "lvl1", "lvl2", "lvl3", "deep_leaf"):
        assert fn in top, (fn, top)
    # root-first order
    idx = [top.index(fn) for fn in
           ("main", "lvl1", "lvl2", "lvl3", "deep_leaf")]
    assert idx == sorted(idx), top


@pytest.mark.skipif(not _perf_available(), reason="perf_event unavailable")
def test_dwarf_off_fp_omitted_is_shallow(fp_omitted_binary):
    """Control: without the unwinder the same binary cannot produce the
    full chain (documents what the DWARF path adds)."""
    from deepflow_tpu.agent.extprofiler import ExternalProfiler
    proc = subprocess.Popen([fp_omitted_binary])
    try:
        time.sleep(0.2)
        batches = []
        prof = ExternalProfiler(batches.append, pid=proc.pid, hz=199,
                                window_s=0.5, dwarf=False).start()
        time.sleep(1.5)
        prof.stop()
    finally:
        proc.kill()
    full = [s.stack for b in batches for s in b
            if all(fn in s.stack for fn in
                   ("main", "lvl1", "lvl2", "lvl3", "deep_leaf"))]
    assert not full, full[:3]
