"""Golden pcap replay harness: every L7 parser has at least one checked-in
capture whose parse result is pinned.

Reference analog: agent/resources/test/ + flow_map.rs:3413 (replay each
.pcap, compare against .result). Both engines replay the same bytes: the
pure-Python FlowMap and the native C++ map must agree with the pinned
expectations.
"""

import json
import os

import pytest

from deepflow_tpu.agent.dispatcher import Dispatcher
from deepflow_tpu.proto import pb

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "pcaps")

CASES = sorted(
    fn[:-5] for fn in os.listdir(FIXTURE_DIR) if fn.endswith(".pcap")
) if os.path.isdir(FIXTURE_DIR) else []


def _replay(name: str, engine: str):
    l7_rows = []

    class Collector:
        def send(self, mt, payload):
            from deepflow_tpu.codec import MessageType
            if mt == MessageType.L7_LOG:
                batch = pb.FlowLogBatch.FromString(payload)
                l7_rows.extend(batch.l7)
            return True

    disp = Dispatcher(sender=Collector(), engine=engine)
    disp.replay_pcap(os.path.join(FIXTURE_DIR, f"{name}.pcap"))
    return l7_rows


def _check(rows, expect):
    assert len(rows) == expect["records"], \
        f"expected {expect['records']} records, got {len(rows)}"
    if not rows:
        return
    if "request_types" in expect:
        assert sorted(r.request_type for r in rows) == \
            sorted(expect["request_types"])
    row = rows[0]
    assert row.l7_protocol == expect["l7_protocol"], \
        f"protocol {row.l7_protocol} != {expect['l7_protocol']}"
    for field in ("request_type", "request_domain", "request_resource",
                  "endpoint", "request_id", "response_result", "version"):
        if field in expect:
            assert str(getattr(row, field)) == str(expect[field]), \
                f"{field}: {getattr(row, field)!r} != {expect[field]!r}"
    if "response_code" in expect:
        assert row.response_code == expect["response_code"]
    if "response_status" in expect:
        assert row.response_status == expect["response_status"]


def test_corpus_exists_and_covers_parsers():
    """Every protocol in the enum with a parser has a golden capture."""
    assert len(CASES) >= 22, CASES
    from deepflow_tpu.agent.protocol_logs.base import get_parser
    covered = set()
    for name in CASES:
        with open(os.path.join(FIXTURE_DIR, f"{name}.result")) as f:
            covered.add(json.load(f)["l7_protocol"])
    enum_values = {v.number for v in
                   pb.L7FlowLog.DESCRIPTOR.fields_by_name[
                       "l7_protocol"].enum_type.values if v.number}
    parsed_protos = {p for p in enum_values if get_parser(p) is not None}
    missing = parsed_protos - covered
    assert not missing, f"parsers without golden captures: {missing}"


@pytest.mark.parametrize("name", CASES)
def test_golden_replay_python_engine(name):
    with open(os.path.join(FIXTURE_DIR, f"{name}.result")) as f:
        expect = json.load(f)
    _check(_replay(name, engine="python"), expect)


@pytest.mark.parametrize("name", CASES)
def test_golden_replay_native_engine(name):
    pytest.importorskip("deepflow_tpu.agent.native_flow")
    with open(os.path.join(FIXTURE_DIR, f"{name}.result")) as f:
        expect = json.load(f)
    _check(_replay(name, engine="native"), expect)
