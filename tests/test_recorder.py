"""Resource recorder: snapshot-diff of nodes/services/workloads/pods into
a queryable resource-change timeline (VERDICT r04 next #9).

Reference analog: controller/recorder/ cache+updaters (resource diffs ->
events). The test drives watch-stream changes through genesis into
event.event and queries the timeline back.
"""

import json
import time

from deepflow_tpu.server import Server
from deepflow_tpu.server.genesis import K8sGenesis
from deepflow_tpu.server.platform_info import PodIpIndex, ResourceIndex
from deepflow_tpu.server.recorder import ResourceRecorder


def _pod(name, ns="prod", node="n1", ip="10.244.1.5", owner=None):
    meta = {"name": name, "namespace": ns}
    if owner:
        meta["ownerReferences"] = [{"kind": "StatefulSet", "name": owner}]
    return {"metadata": meta, "spec": {"nodeName": node},
            "status": {"podIP": ip, "podIPs": [{"ip": ip}]}}


def test_recorder_attr_diff_cycle():
    rows = []
    rec = ResourceRecorder(rows.extend)
    rec.observe("node", "n1", {"az": "us-a", "ready": "True"})
    rec.observe("node", "n1", {"az": "us-a", "ready": "True"})  # no-op
    rec.observe("node", "n1", {"az": "us-a", "ready": "False"})
    rec.observe("node", "n1", None, deleted=True)
    assert [r["event_type"] for r in rows] == [
        "node-added", "node-modified", "node-deleted"]
    changed = json.loads(rows[1]["attrs"])["changed"]
    assert changed == {"ready": {"before": "True", "after": "False"}}
    assert json.loads(rows[2]["attrs"])["before"]["az"] == "us-a"
    assert "ready: True->False" in rows[1]["description"]


def test_recorder_reconcile_emits_gap_deletions():
    rows = []
    rec = ResourceRecorder(rows.extend)
    rec.observe("service", "p/a", {"cluster_ip": "1.2.3.4"}, emit=False)
    rec.observe("service", "p/b", {"cluster_ip": "1.2.3.5"}, emit=False)
    n = rec.reconcile("service", {"p/a"})
    assert n == 1
    assert [r["event_type"] for r in rows] == ["service-deleted"]
    assert rows[0]["resource_name"] == "p/b"


def test_node_service_workload_events_through_genesis():
    """Node readiness flips, service port changes, and derived workload
    lifecycle all land as diff events."""
    rows = []
    gen = K8sGenesis(PodIpIndex(), api_base="http://127.0.0.1:1",
                     event_sink=lambda r: rows.extend(r),
                     resources=ResourceIndex())
    node = {"metadata": {"name": "n1", "labels": {
                "topology.kubernetes.io/zone": "us-a"}},
            "spec": {"podCIDR": "10.244.0.0/24"},
            "status": {"addresses": [
                {"type": "InternalIP", "address": "10.0.0.1"}],
                "conditions": [{"type": "Ready", "status": "True"}]}}
    gen._apply_node("ADDED", node)
    node["status"]["conditions"][0]["status"] = "False"
    gen._apply_node("MODIFIED", node)
    svc = {"metadata": {"name": "web", "namespace": "prod"},
           "spec": {"clusterIP": "10.96.0.10", "type": "ClusterIP",
                    "ports": [{"port": 80}]}}
    gen._apply_service("ADDED", svc)
    svc["spec"]["ports"] = [{"port": 80}, {"port": 443}]
    gen._apply_service("MODIFIED", svc)
    gen._apply("ADDED", _pod("db-0", owner="db"))
    gen._apply("ADDED", _pod("db-1", ip="10.244.1.6", owner="db"))
    gen._apply("DELETED", _pod("db-0", owner="db"))
    gen._apply("DELETED", _pod("db-1", ip="10.244.1.6", owner="db"))

    types = [r["event_type"] for r in rows]
    assert "node-added" in types and "node-modified" in types
    assert "service-modified" in types
    assert types.count("workload-added") == 1   # first pod only
    assert types.count("workload-deleted") == 1  # last pod only
    nm = next(r for r in rows if r["event_type"] == "node-modified")
    assert json.loads(nm["attrs"])["changed"]["ready"] == {
        "before": "True", "after": "False"}
    sm = next(r for r in rows if r["event_type"] == "service-modified")
    assert json.loads(sm["attrs"])["changed"]["ports"] == {
        "before": [80], "after": [80, 443]}


def test_change_timeline_queryable():
    """End to end: diff events land in event.event and come back from
    DF-SQL as the what-changed-before-the-regression timeline."""
    server = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    try:
        sink = server.genesis_event_sink \
            if hasattr(server, "genesis_event_sink") else None
        rows_sink = (lambda rows:
                     server.db.table("event.event").append_rows(rows))
        gen = K8sGenesis(PodIpIndex(), api_base="http://127.0.0.1:1",
                         event_sink=sink or rows_sink,
                         resources=ResourceIndex())
        node = {"metadata": {"name": "n1", "labels": {}},
                "spec": {},
                "status": {"addresses": [
                    {"type": "InternalIP", "address": "10.0.0.1"}],
                    "conditions": [{"type": "Ready", "status": "True"}]}}
        gen._apply_node("ADDED", node)
        node["status"]["conditions"][0]["status"] = "False"
        gen._apply_node("MODIFIED", node)
        assert server.wait_for_rows("event.event", 2, timeout=5)
        from deepflow_tpu.query import execute
        t = server.db.table("event.event")
        r = execute(t, "SELECT time, event_type, resource_name, attrs "
                       "FROM t WHERE resource_type = 'node' ORDER BY time")
        assert [row[1] for row in r.values] == ["node-added",
                                                "node-modified"]
        attrs = json.loads(r.values[-1][3])
        assert attrs["changed"]["ready"]["after"] == "False"
    finally:
        server.stop()
