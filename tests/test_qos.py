"""Closed-loop overload control & multi-tenant QoS (deepflow_tpu/qos).

Unit coverage for the subsystem's invariants: token-bucket quotas are
all-or-nothing with refill; DRR delivers weighted shares under
contention; HIGH-class frames are never quota-shed (and queue_full
sheds withhold the ack while quota sheds observe it); pressure levels
rise immediately and decay with hysteresis; adaptive sampling is
deterministic, always keeps exemplars, and conserves on its hop
ledger; the controller stamps ``SyncResponse.qos`` and the agent
degrades/restores its probes from it; sender reconnect replay orders
HIGH before MID/LOW.
"""

import threading
import time
import types

import pytest

from deepflow_tpu.codec import MessageType, priority_of
from deepflow_tpu.qos import (
    AdaptiveSampler, AdmissionQueues, PressureController, Qos, QosConfig,
    TenantQos, TokenBucket, sample_hash01)


class _RecHop:
    """Hop-ledger stand-in: accumulates the same counters."""

    def __init__(self):
        self.emitted = 0
        self.delivered = 0
        self.dropped = 0
        self.reasons = {}

    def account(self, emitted=0, delivered=0, dropped=0, reason=None):
        self.emitted += emitted
        self.delivered += delivered
        self.dropped += dropped
        if dropped and reason:
            self.reasons[reason] = self.reasons.get(reason, 0) + dropped


class _FakeTelemetry:
    def __init__(self):
        self.h = _RecHop()

    def hop(self, name):
        return self.h


# -- token bucket -------------------------------------------------------------

def test_token_bucket_all_or_nothing_and_refill():
    b = TokenBucket(100.0, burst=10.0)
    assert b.take(10)          # full burst drains in one take
    assert not b.take(10)      # empty: all-or-nothing, nothing partial
    time.sleep(0.2)            # ~20 tokens refill, capped at burst 10
    assert b.take(10)
    assert not b.take(1000)    # can never exceed burst even after a wait


def test_token_bucket_zero_rate_is_unlimited():
    b = TokenBucket(0.0)
    assert b.take(1_000_000)
    assert b.take(1_000_000)


# -- admission / DRR ----------------------------------------------------------

def _group(n):
    return [(None, b"")] * n


def test_drr_delivers_weighted_shares_under_contention():
    cfg = QosConfig()
    cfg.set_tenant(TenantQos(org_id=1, weight=3))
    cfg.set_tenant(TenantQos(org_id=2, weight=1))
    deliveries = []
    lock = threading.Lock()

    def deliver(msg_type, lane, enq_ns, group):
        with lock:
            deliveries.append((lane, len(group)))  # lane carries the org
        return True

    aq = AdmissionQueues(cfg, deliver)
    # backlog BOTH tenants before the drain starts so every DRR
    # rotation sees contention
    per_org = 960
    for org in (1, 2):
        for _ in range(per_org // 8):
            assert aq.submit(org, 1, MessageType.METRICS, org,
                             _group(8), 0) == "admitted"
    aq.start()
    deadline = time.monotonic() + 10
    while aq.stats["delivered"] < 2 * per_org \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    aq.stop()
    assert aq.stats["delivered"] == 2 * per_org
    # during the contended first half, org 1 (weight 3) must get
    # roughly 3x org 2's frames — allow 2x..4x for rotation phase
    with lock:
        half, counts = 0, {1: 0, 2: 0}
        for lane, n in deliveries:
            counts[lane] += n
            half += n
            if half >= per_org:
                break
    assert counts[2] > 0, "weight-1 tenant starved"
    ratio = counts[1] / counts[2]
    assert 2.0 <= ratio <= 4.0, (ratio, counts)


def test_high_never_quota_shed_and_ack_discipline():
    cfg = QosConfig()
    cfg.set_tenant(TenantQos(org_id=5, weight=1, rate_fps=1.0, burst=4.0))
    hop = _RecHop()
    observed = []
    aq = AdmissionQueues(cfg, lambda *a: True, hop=hop,
                         observe_seqs=observed.append)
    # MID within burst admits, then the bucket is dry -> quota shed,
    # and the shed group IS observed (acked: policy, not pressure)
    assert aq.submit(5, 1, MessageType.METRICS, 0, _group(4), 0) \
        == "admitted"
    assert aq.submit(5, 1, MessageType.METRICS, 0, _group(4), 0) == "quota"
    assert len(observed) == 1 and len(observed[0]) == 4
    assert hop.reasons == {"quota": 4}
    # HIGH sails past the same dry bucket — quota never sheds HIGH
    assert aq.submit(5, 0, MessageType.L7_LOG, 0, _group(4), 0) \
        == "admitted"
    snap = aq.tenant_snapshot()[5]
    assert snap["shed_quota"] == 4
    assert snap["admitted"] == 8
    assert snap["depth"] == {"high": 4, "mid": 4, "low": 0}


def test_high_queue_full_is_unacked_backpressure():
    cfg = QosConfig(queue_frames=4, high_block_s=0.05)
    hop = _RecHop()
    observed = []
    aq = AdmissionQueues(cfg, lambda *a: True, hop=hop,
                         observe_seqs=observed.append)
    # no drain running: the HIGH queue fills and stays full
    assert aq.submit(7, 0, MessageType.L7_LOG, 0, _group(4), 0) \
        == "admitted"
    t0 = time.monotonic()
    assert aq.submit(7, 0, MessageType.L7_LOG, 0, _group(1), 0) \
        == "queue_full"
    # it WAITED for the drain first (that wait is the backpressure) ...
    assert time.monotonic() - t0 >= 0.04
    # ... and the shed is NOT observed: ack withheld -> retransmit
    assert observed == []
    assert hop.reasons == {"queue_full": 1}
    assert aq.tenant_snapshot()[7]["shed_queue_full"] == 1
    assert aq.tenant_snapshot()[7]["high_wait_ns"] > 0


def test_admission_conserves_on_hop_ledger():
    cfg = QosConfig()
    cfg.set_tenant(TenantQos(org_id=9, weight=1, rate_fps=1.0, burst=8.0))
    hop = _RecHop()
    aq = AdmissionQueues(cfg, lambda *a: True, hop=hop,
                         observe_seqs=lambda g: None)
    for _ in range(6):
        aq.submit(9, 1, MessageType.METRICS, 0, _group(4), 0)
    aq.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with aq._lock:
            if all(t.total_depth() == 0 for t in aq._tenants.values()):
                break
        time.sleep(0.01)
    aq.stop()
    # receiver accounts emitted=24 upstream; admission splits the rest
    assert hop.delivered + hop.dropped == 24
    assert hop.delivered == aq.stats["delivered"]
    assert hop.reasons.get("quota", 0) == aq.stats["shed_quota"] > 0


# -- pressure controller ------------------------------------------------------

def test_pressure_raises_immediately_and_decays_stepwise():
    cfg = QosConfig(decay_s=0.15)
    fill = {"v": 0.0}
    pc = PressureController(cfg, decoder_fill=lambda: fill["v"])
    fill["v"] = 0.95
    pc.evaluate_once()
    assert pc.level(0) == 3                   # critical bites at once
    fill["v"] = 0.0
    pc.evaluate_once()
    assert pc.level(0) == 3                   # hysteresis holds the level
    time.sleep(0.2)
    pc.evaluate_once()
    assert pc.level(0) == 2                   # one notch per decay_s
    pc.evaluate_once()
    assert pc.level(0) == 2                   # not two notches at once
    time.sleep(0.2)
    pc.evaluate_once()
    assert pc.level(0) == 1
    fill["v"] = 0.80
    pc.evaluate_once()
    assert pc.level(0) == 2                   # re-raise is immediate
    assert pc.stats["raises"] >= 2 and pc.stats["decays"] == 2
    d = pc.directive(42)
    assert d["pressure_level"] == 2
    assert d["sample_rate"] == cfg.sample_rates[2]
    assert d["weight"] == 1 and d["rate_fps"] == 0.0


# -- adaptive sampling --------------------------------------------------------

class _FakePressure:
    def __init__(self, lvl=0):
        self.lvl = lvl

    def level(self, org_id=0):
        return self.lvl


def test_sampler_is_deterministic_and_rate_accurate():
    tele = _FakeTelemetry()
    sampler = AdaptiveSampler(QosConfig(), pressure=_FakePressure(2),
                              telemetry=tele)  # level 2 -> rate 0.5
    first = [sampler.keep(7, k) for k in range(2000)]
    kept = sum(first)
    assert 800 < kept < 1200                   # ~0.5 on a uniform hash
    # identical keys -> identical decisions (replay/retransmit safe)
    assert [sampler.keep(7, k) for k in range(2000)] == first
    assert sample_hash01(7, 123) == sample_hash01(7, 123)
    assert sample_hash01(7, 123) != sample_hash01(8, 123)
    # conservation on the qos.sample hop
    h = tele.h
    assert h.emitted == h.delivered + h.dropped == 4000
    assert h.reasons == {"adaptive_sample": h.dropped}


def test_sampler_always_keeps_exemplars():
    sampler = AdaptiveSampler(QosConfig(sample_rates=(1.0, 1.0, 0.5, 0.0)),
                              pressure=_FakePressure(3))
    assert all(sampler.keep(3, k, exemplar=True) for k in range(100))
    assert not any(sampler.keep(3, k) for k in range(100))  # rate 0 bulk
    st = sampler.snapshot()["3"]
    assert st["exemplars"] == 100 and st["kept"] == 100
    assert st["dropped"] == 100 and st["rate"] == 0.0
    assert sampler.is_slow_ns(int(600e6))      # 600ms >= 500ms default
    assert not sampler.is_slow_ns(int(10e6))


# -- directive plumbing (controller Sync -> agent) ----------------------------

def test_qos_directive_rides_sync_response():
    grpc = pytest.importorskip("grpc")
    from deepflow_tpu.proto import pb
    from deepflow_tpu.server.controller import Controller
    from deepflow_tpu.server.platform_info import PlatformInfoTable

    qos = Qos(QosConfig())
    qos.attach(lambda *a: True, decoder_fill=lambda: 0.95)
    qos.pressure.evaluate_once()               # global -> critical
    ctrl = Controller(PlatformInfoTable(), host="127.0.0.1", port=0,
                      qos=qos).start()
    ch = None
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{ctrl.port}")
        sync = ch.unary_unary(
            "/deepflow_tpu.Synchronizer/Sync",
            request_serializer=pb.SyncRequest.SerializeToString,
            response_deserializer=pb.SyncResponse.FromString)
        resp = sync(pb.SyncRequest(ctrl_ip="10.9.0.1",
                                   hostname="qos-agent"), timeout=10)
        assert resp.HasField("qos")
        assert resp.qos.pressure_level == 3
        assert abs(resp.qos.sample_rate
                   - QosConfig().sample_rates[3]) < 1e-9
        assert resp.qos.weight == 1
        assert resp.qos.updated_ns > 0
    finally:
        if ch is not None:
            ch.close()
        ctrl.stop()


def test_disabled_qos_stamps_no_directive():
    grpc = pytest.importorskip("grpc")
    from deepflow_tpu.proto import pb
    from deepflow_tpu.server.controller import Controller
    from deepflow_tpu.server.platform_info import PlatformInfoTable

    cfg = QosConfig()
    cfg.enabled = False
    ctrl = Controller(PlatformInfoTable(), host="127.0.0.1", port=0,
                      qos=Qos(cfg)).start()
    ch = None
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{ctrl.port}")
        sync = ch.unary_unary(
            "/deepflow_tpu.Synchronizer/Sync",
            request_serializer=pb.SyncRequest.SerializeToString,
            response_deserializer=pb.SyncResponse.FromString)
        resp = sync(pb.SyncRequest(ctrl_ip="10.9.0.2",
                                   hostname="no-qos"), timeout=10)
        assert not resp.HasField("qos")
    finally:
        if ch is not None:
            ch.close()
        ctrl.stop()


def test_agent_backpressure_scales_probes_and_restores():
    from deepflow_tpu.agent.agent import Agent
    from deepflow_tpu.agent.config import AgentConfig

    a = Agent.__new__(Agent)                   # no sockets, no threads
    a.config = AgentConfig()
    a.pressure_level = 0
    a._profiler_lock = threading.Lock()
    hz = a.config.profiler.sample_hz
    emit = a.config.profiler.emit_interval_s
    a.sampler = types.SimpleNamespace(
        period_s=1.0 / hz, period_us=int(1_000_000 / hz),
        emit_interval_s=emit)
    a.tpuprobe = None

    a.apply_backpressure(2)
    assert a.pressure_level == 2
    want_hz = max(1.0, hz * a.config.qos.hz_scale[2])
    assert abs(a.sampler.period_s - 1.0 / want_hz) < 1e-9
    assert a.sampler.emit_interval_s == emit * a.config.qos.emit_scale[2]

    a.apply_backpressure(0)                    # level 0 restores exactly
    assert a.pressure_level == 0
    assert abs(a.sampler.period_s - 1.0 / hz) < 1e-9
    assert a.sampler.emit_interval_s == emit

    a.apply_backpressure(99)                   # clamped to 3
    assert a.pressure_level == 3
    a.config.qos.enabled = False               # kill switch: inert
    a.apply_backpressure(0)
    assert a.pressure_level == 3


# -- sender replay priority (satellite: HIGH before MID/LOW) ------------------

class _FakeSpool:
    on_evict = None

    def __init__(self, entries):
        self.entries = entries                 # (msg_type_int, seq, payload)

    def replay(self, after_seq):
        return [e for e in self.entries if e[1] > after_seq]

    def pending_records(self):
        return len(self.entries)

    def max_seq(self):
        return max((e[1] for e in self.entries), default=0)

    def min_pending_seq(self):
        return min((e[1] for e in self.entries), default=0)


def test_reconnect_retransmit_replays_high_class_first():
    from deepflow_tpu.agent.sender import UniformSender, _Frame

    s = UniformSender([("127.0.0.1", 1)], durable=True)
    base = s.seq_base
    arrived = [(MessageType.DFSTATS, 1), (MessageType.L7_LOG, 2),
               (MessageType.METRICS, 3), (MessageType.L7_LOG, 4),
               (MessageType.DFSTATS, 5)]
    for mt, i in arrived:
        s._unacked[base + i] = _Frame(mt, b"", base + i, None)
    s._close()
    got = [(f.msg_type, f.seq - base) for f in s._pending]
    assert got == [(MessageType.L7_LOG, 2), (MessageType.L7_LOG, 4),
                   (MessageType.METRICS, 3), (MessageType.DFSTATS, 1),
                   (MessageType.DFSTATS, 5)]
    # class-major, seq within class — never plain seq order
    assert [priority_of(mt) for mt, _ in got] == sorted(
        priority_of(mt) for mt, _ in got)


def test_spool_replay_orders_high_before_mid_low():
    from deepflow_tpu.agent.sender import UniformSender

    spool = _FakeSpool([(int(MessageType.DFSTATS), 101, b"a"),
                        (int(MessageType.L7_LOG), 102, b"b"),
                        (int(MessageType.METRICS), 103, b"c"),
                        (int(MessageType.L7_LOG), 104, b"d")])
    s = UniformSender([("127.0.0.1", 1)], durable=True, spool=spool)
    s._load_replay()
    assert [f.msg_type for f in s._pending] == [
        MessageType.L7_LOG, MessageType.L7_LOG, MessageType.METRICS,
        MessageType.DFSTATS]
    assert s.stats["replayed"] == 4
