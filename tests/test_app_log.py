"""application_log.log: dedicated log store (reference:
server/ingester/app_log — untruncated body, severity, trace join)."""

import json
import urllib.request

import pytest

from deepflow_tpu.server import Server


def _post(port: int, path: str, obj) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=5).read())


@pytest.fixture
def server():
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0).start()
    yield s
    s.stop()


def test_log_roundtrip_untruncated(server):
    big = "x" * 5000 + "-END"  # far past the old 1024-char event cap
    out = _post(server.query_port, "/api/v1/log", {
        "service": "checkout", "message": big, "level": "error",
        "trace_id": "abc123", "span_id": "s1", "timestamp_ns": 1_000,
        "custom": "v"})
    assert out["accepted"] == 1
    res = _post(server.query_port, "/v1/log/search",
                {"app_service": "checkout"})["result"]
    assert res["count"] == 1
    row = res["logs"][0]
    assert row["body"] == big                  # untruncated
    assert row["severity_number"] == 17        # error
    assert row["severity_text"] == "error"
    assert row["trace_id"] == "abc123"
    assert json.loads(row["attrs"])["custom"] == "v"


def test_log_joins_trace(server):
    tid = "deadbeefcafe0001"
    # a trace span and a log line sharing the trace id
    _post(server.query_port, "/api/v1/otlp/traces", {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "checkout"}}]},
            "scopeSpans": [{"spans": [{
                "traceId": tid, "spanId": "aaa", "name": "GET /pay",
                "startTimeUnixNano": 1000, "endTimeUnixNano": 2000}]}]}]})
    _post(server.query_port, "/api/v1/log", {
        "service": "checkout", "message": "payment failed",
        "level": "warn", "trace_id": tid})
    res = _post(server.query_port, "/v1/log/search",
                {"trace_id": tid})["result"]
    assert res["count"] == 1
    assert res["logs"][0]["body"] == "payment failed"
    # and the trace itself is assemblable
    tree = _post(server.query_port, "/v1/trace/Tracing",
                 {"trace_id": tid})["result"]
    assert tree["span_count"] == 1


def test_otlp_logs_ingest(server):
    out = _post(server.query_port, "/api/v1/otlp/logs", {
        "resourceLogs": [{
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": "svc-a"}},
                {"key": "service.instance.id",
                 "value": {"stringValue": "pod-1"}}]},
            "scopeLogs": [{"logRecords": [
                {"timeUnixNano": "123456789", "severityNumber": 9,
                 "severityText": "INFO",
                 "body": {"stringValue": "started ok"},
                 "traceId": "t1", "spanId": "s1",
                 "attributes": [{"key": "k",
                                 "value": {"stringValue": "v"}}]},
                {"severityNumber": 17, "severityText": "ERROR",
                 "body": {"stringValue": "boom"}},
            ]}]}]})
    assert out["accepted"] == 2
    res = _post(server.query_port, "/v1/log/search",
                {"min_severity": 17})["result"]
    assert res["count"] == 1
    assert res["logs"][0]["body"] == "boom"
    res = _post(server.query_port, "/v1/log/search",
                {"query": "started"})["result"]
    assert res["count"] == 1
    assert res["logs"][0]["app_instance"] == "pod-1"
    assert res["logs"][0]["time"] == 123456789


def test_otlp_structured_body_and_bad_resource(server):
    # structured AnyValue bodies must not be silently emptied
    out = _post(server.query_port, "/api/v1/otlp/logs", {
        "resourceLogs": [{
            "scopeLogs": [{"logRecords": [
                {"body": {"intValue": "42"}},
                {"body": {"kvlistValue": {"values": [
                    {"key": "k", "value": {"stringValue": "v"}}]}}},
            ]}]}]})
    assert out["accepted"] == 2
    res = _post(server.query_port, "/v1/log/search", {})["result"]
    bodies = {r["body"] for r in res["logs"]}
    assert "42" in bodies
    assert any("kvlistValue" in b for b in bodies)
    # malformed resource is a 400, not a 500
    import urllib.error
    try:
        _post(server.query_port, "/api/v1/otlp/logs",
              {"resourceLogs": [{"resource": []}]})
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_dictionary_compaction_after_ttl():
    """TTL trim + compaction bounds the body dictionary (review finding:
    append-only dictionaries would otherwise retain every distinct log
    line forever)."""
    from deepflow_tpu.server.janitor import Janitor
    from deepflow_tpu.store.db import Database
    db = Database()
    t = db.table("application_log.log")
    t.chunk_rows = 1024
    old_ns = 1_000_000_000 * 1_000_000_000       # ancient
    t.append_rows([{"time": old_ns, "body": f"old-line-{i}"}
                   for i in range(8192)])
    t.append_rows([{"time": 2_000_000_000 * 1_000_000_000,
                    "body": "fresh"}])
    t.flush()
    assert len(t.dicts["body"]) > 8192
    jan = Janitor(db)
    jan.sweep(now_s=2_000_000_000)               # old rows past TTL
    assert len(t) == 1
    assert len(t.dicts["body"]) == 2             # "" + "fresh"
    # remap kept the surviving row decodable
    ch = t.snapshot()[0]
    assert t.dicts["body"].decode(int(ch["body"][0])) == "fresh"


def test_log_sql_and_ttl(server):
    _post(server.query_port, "/api/v1/log",
          {"service": "s1", "message": "m1", "level": "info"})
    out = _post(server.query_port, "/v1/query/", {
        "sql": "SELECT app_service, severity_number, body FROM "
               "application_log.log"})
    rows = out["result"]
    assert rows["values"][0][rows["columns"].index("body")] == "m1"
    from deepflow_tpu.server.janitor import DEFAULT_TTL_S
    assert "application_log.log" in DEFAULT_TTL_S


def test_log_search_newest_first_and_limit(server):
    for i in range(5):
        _post(server.query_port, "/api/v1/log",
              {"service": "s", "message": f"line-{i}",
               "timestamp_ns": 1000 + i})
    res = _post(server.query_port, "/v1/log/search",
                {"limit": 2})["result"]
    assert [r["body"] for r in res["logs"]] == ["line-4", "line-3"]
