"""OTA upgrade with binary distribution (VERDICT r04 missing #6):
upload a versioned package to the controller repo, roll it out to an
agent over the sync plane — the agent downloads, verifies the digest,
stages the tree, and re-execs with it first on PYTHONPATH.

Reference analog: message/agent.proto:9 Upgrade stream +
cli/ctl/agent.go:135 (deepflow-ctl repo agent upload / agent upgrade).
"""

import base64
import hashlib
import io
import json
import os
import tarfile
import time
import urllib.request

import pytest

from deepflow_tpu.agent.agent import Agent
from deepflow_tpu.agent.config import AgentConfig
from deepflow_tpu.server import Server


def _make_package(marker: str) -> bytes:
    """A tiny package tree: new_agent/version.py carrying a marker."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as t:
        data = f'VERSION = "{marker}"\n'.encode()
        info = tarfile.TarInfo("new_agent/version.py")
        info.size = len(data)
        t.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return json.load(urllib.request.urlopen(req))


@pytest.fixture
def server():
    s = Server(host="127.0.0.1", ingest_port=0, query_port=0, sync_port=0,
               enable_controller=True).start()
    yield s
    s.stop()


def test_repo_upload_list_fetch(server):
    pkg = _make_package("v9")
    out = _post(server.query_port, "/v1/repo",
                {"action": "upload", "name": "agent", "version": "v9",
                 "data_b64": base64.b64encode(pkg).decode()})
    up = out["uploaded"]
    assert up["sha256"] == hashlib.sha256(pkg).hexdigest()
    listing = _post(server.query_port, "/v1/repo", {})["packages"]
    assert listing["agent"][0]["version"] == "v9"
    # grpc fetch returns the same bytes + digest; latest wins when
    # version is empty
    got = server.controller.packages.get("agent", "")
    assert got is not None
    version, data, sha = got
    assert version == "v9" and data == pkg
    assert server.controller.packages.get("agent", "nope") is None


def test_repo_rejects_bad_upload(server):
    import urllib.error
    try:
        _post(server.query_port, "/v1/repo",
              {"action": "upload", "version": "v1", "data_b64": "!!!"})
        raise AssertionError("bad base64 accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 400
    try:
        _post(server.query_port, "/v1/repo",
              {"action": "upload", "version": "",
               "data_b64": base64.b64encode(b"x").decode()})
        raise AssertionError("empty version accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_ota_rollout_stages_and_reexecs(server, tmp_path, monkeypatch):
    """Full rollout: package in repo -> upgrade version=vX command ->
    agent fetches over sync plane, verifies, stages, re-execs with the
    staged tree on PYTHONPATH."""
    monkeypatch.setenv("DF_UPGRADE_DIR", str(tmp_path / "versions"))
    pkg = _make_package("v2-marker")
    _post(server.query_port, "/v1/repo",
          {"action": "upload", "name": "agent", "version": "v2",
           "data_b64": base64.b64encode(pkg).decode()})

    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.controller = f"127.0.0.1:{server.controller.port}"
    cfg.standalone = False
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    cfg.sync_interval_s = 0.2
    cfg.socket_scan_interval_s = 0
    agent = Agent(cfg).start()
    execs = []
    try:
        from deepflow_tpu.agent import ops
        monkeypatch.setattr(
            ops.CommandRegistry, "_execv",
            staticmethod(lambda *a: execs.append(a)))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["syncs"] == 0:
            time.sleep(0.05)
        code, out = agent.synchronizer._ops.run("upgrade",
                                                ["version=v2"])
        assert code == 0, out
        result = json.loads(out)
        assert result["upgrading"] is True
        assert result["version"] == "v2"
        staged = result["staged"]
        assert staged and os.path.isdir(staged)
        with open(os.path.join(staged, "new_agent", "version.py")) as f:
            assert "v2-marker" in f.read()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not execs:
            time.sleep(0.05)
        assert execs, "re-exec never fired"
        assert staged in os.environ.get("PYTHONPATH", "")
    finally:
        try:
            agent.stop()
        except Exception:
            pass


def test_ota_digest_and_missing_version_fail_closed(server, tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("DF_UPGRADE_DIR", str(tmp_path / "versions"))
    cfg = AgentConfig()
    cfg.sender.servers = [("127.0.0.1", server.ingest_port)]
    cfg.controller = f"127.0.0.1:{server.controller.port}"
    cfg.standalone = False
    cfg.profiler.enabled = False
    cfg.tpuprobe.enabled = False
    cfg.guard.enabled = False
    cfg.sync_interval_s = 0.2
    cfg.socket_scan_interval_s = 0
    agent = Agent(cfg).start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                agent.synchronizer.stats["syncs"] == 0:
            time.sleep(0.05)
        code, out = agent.synchronizer._ops.run(
            "upgrade", ["version=does-not-exist"])
        res = json.loads(out)
        assert res["upgrading"] is False and "not in repo" in res["error"]
        # a package with an unsafe member must refuse to stage
        evil = io.BytesIO()
        with tarfile.open(fileobj=evil, mode="w:gz") as t:
            data = b"boom"
            info = tarfile.TarInfo("../escape.py")
            info.size = len(data)
            t.addfile(info, io.BytesIO(data))
        server.controller.packages.upload("agent", "evil",
                                          evil.getvalue())
        code, out = agent.synchronizer._ops.run("upgrade",
                                                ["version=evil"])
        res = json.loads(out)
        assert res["upgrading"] is False and "unsafe" in res["error"]
    finally:
        agent.stop()
