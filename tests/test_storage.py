"""Tiered on-disk storage: segments, crash recovery, eviction, rollup
datasource selection, and the durability gate (ISSUE 9)."""

import json
import os

import numpy as np
import pytest

from deepflow_tpu.query import execute
from deepflow_tpu.query import datasource as qds
from deepflow_tpu.query import sql as S
from deepflow_tpu.query.cache import QueryCache
from deepflow_tpu.server.datasource import RollupJob
from deepflow_tpu.server.flusher import DurabilityGate, Flusher
from deepflow_tpu.server.janitor import Janitor
from deepflow_tpu.server.receiver import Receiver, SeqAckTracker
from deepflow_tpu.store import Database
from deepflow_tpu.store.segment import Segment, SegmentError, write_segment
from deepflow_tpu.store.tiered import TieredStore
from deepflow_tpu.telemetry import Telemetry


# -- segment file format ----------------------------------------------------

def _chunk(n=100, t0=1000):
    return {"time": np.arange(t0, t0 + n, dtype=np.uint32),
            "v": np.arange(n, dtype=np.uint64),
            # single-valued -> takes the const codec path
            "tag": np.zeros(n, dtype=np.uint32)}


def test_segment_roundtrip(tmp_path):
    # pinned to the frozen v1 writer: the zero-copy-view guarantee below
    # is a v1 raw-block property (v2 codecs decode into fresh arrays and
    # are covered by tests/test_segment_v2.py)
    p = str(tmp_path / "seg_00000001.seg")
    ch = _chunk()
    write_segment(p, ch, time_col="time",
                  dict_gens={"tag": (0, 17)}, fmt=1)
    seg = Segment.open(p)
    assert seg.rows == 100
    assert (seg.tmin, seg.tmax) == (1000, 1099)
    assert seg.dict_gens == {"tag": (0, 17)}
    out = seg.chunk()
    for name in ch:
        assert np.array_equal(out[name], ch[name]), name
    # raw blocks are zero-copy views over the mapping, not copies
    assert not out["time"].flags.writeable


def test_segment_codecs(tmp_path):
    """Per-column codec choice in the frozen v1 writer: const for
    single-valued columns (one element on disk), zlib only when it pays,
    raw otherwise — and compress=False keeps const but never deflates.
    The v2 codec set (delta/for/dictrank) is covered by
    tests/test_segment_v2.py."""
    rng = np.random.default_rng(7)
    ch = {"const64": np.full(4096, 0xDEAD, dtype=np.uint64),
          "repeat": np.arange(4096, dtype=np.uint64) % 4,   # compressible
          "noise": rng.integers(0, 2**63, 4096, dtype=np.uint64)}
    p = str(tmp_path / "seg.seg")
    footer = write_segment(p, ch, fmt=1)
    codecs = {k: v["codec"] for k, v in footer["cols"].items()}
    assert codecs == {"const64": "const", "repeat": "zlib",
                      "noise": "raw"}
    assert footer["cols"]["const64"]["nbytes"] == 8  # one element
    seg = Segment.open(p)
    out = seg.chunk()
    for name in ch:
        assert np.array_equal(out[name], ch[name]), name
    # const reads are stride-0 broadcast views: no materialized copy
    assert out["const64"].strides == (0,)
    assert not out["const64"].flags.writeable

    p2 = str(tmp_path / "seg2.seg")
    footer2 = write_segment(p2, ch, compress=False, fmt=1)
    codecs2 = {k: v["codec"] for k, v in footer2["cols"].items()}
    assert codecs2 == {"const64": "const", "repeat": "raw",
                       "noise": "raw"}
    out2 = Segment.open(p2).chunk()
    for name in ch:
        assert np.array_equal(out2[name], ch[name]), name


def test_segment_const_block_validated(tmp_path):
    """A const block whose size disagrees with its dtype is torn."""
    p = str(tmp_path / "seg.seg")
    write_segment(p, {"c": np.full(64, 5, dtype=np.uint64)})
    import struct
    import zlib as _z
    with open(p, "rb") as f:
        buf = bytearray(f.read())
    flen, fcrc, magic = struct.unpack("<II8s", buf[-16:])
    foot = json.loads(bytes(buf[-16 - flen:-16]))
    foot["cols"]["c"]["nbytes"] = 4  # lies about the block size
    fb = json.dumps(foot, sort_keys=True).encode()
    buf = buf[:len(buf) - 16 - flen] + fb + struct.pack(
        "<II8s", len(fb), _z.crc32(fb) & 0xFFFFFFFF, magic)
    with open(p, "wb") as f:
        f.write(buf)
    with pytest.raises(SegmentError, match="const block"):
        Segment.open(p)


def test_segment_torn_tail_detected(tmp_path):
    p = str(tmp_path / "seg.seg")
    write_segment(p, _chunk(), time_col="time")
    size = os.path.getsize(p)
    for cut in (size - 4, size // 2, 10):
        with open(p, "r+b") as f:
            f.truncate(cut)
        with pytest.raises(SegmentError):
            Segment.open(p)
        write_segment(p, _chunk(), time_col="time")
    # flipped footer byte -> crc mismatch
    with open(p, "r+b") as f:
        f.seek(size - 30)
        b = f.read(1)
        f.seek(size - 30)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(SegmentError):
        Segment.open(p)


def test_tiered_recover_drops_uncommitted(tmp_path):
    root = str(tmp_path / "segments")
    ts = TieredStore(root)
    ts.commit({"t": {"chunk": _chunk(), "rows": 100, "time_col": "time",
                     "dicts": {}, "dict_state": {}}})
    # crash mid-commit artifacts: a written-but-unlisted segment and a
    # tmp file must both be deleted on recovery
    orphan = os.path.join(root, "t", "seg_00000099.seg")
    write_segment(orphan, _chunk(50))
    open(os.path.join(root, "t", f"seg_x.seg.tmp.{os.getpid()}"),
         "wb").close()
    ts2 = TieredStore(root)
    ts2.recover()
    assert not os.path.exists(orphan)
    assert ts2.tier("t").rows == 100
    assert ts2.stats["torn_dropped"] == 2


def test_torn_listed_segment_dropped_on_recovery(tmp_path):
    root = str(tmp_path / "segments")
    ts = TieredStore(root)
    ts.commit({"t": {"chunk": _chunk(), "rows": 100, "time_col": "time",
                     "dicts": {}, "dict_state": {}}})
    # still staged (no table confirmed it) but manifest-listed
    tt = ts.tier("t")
    path = os.path.join(tt.dir, tt.manifest_names()[0])
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 8)
    ts2 = TieredStore(root)
    ts2.recover()  # listed but torn: dropped, manifest re-committed
    assert ts2.tier("t").segment_count() == 0
    assert not os.path.exists(path)
    ts3 = TieredStore(root)
    ts3.recover()
    assert ts3.stats["torn_dropped"] == 0  # converged


# -- flush -> restart -> query equality -------------------------------------

_NET_ROW = {"ip_src": "1.1.1.1", "ip_dst": "2.2.2.2", "server_port": 80,
            "protocol": 1, "host": "h1"}


def _fill_net(db, n=120, t0=6000):
    t = db.table("flow_metrics.network.1s")
    t.append_rows([dict(_NET_ROW, time=t0 + i, byte_tx=i, packet_tx=1)
                   for i in range(n)])
    return t


def test_flush_restart_query_equality(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d, storage=True)
    _fill_net(db)
    sql = ("SELECT ip_src, Sum(byte_tx) AS b, Count() AS c FROM t "
           "GROUP BY ip_src")
    before = execute(db.table("flow_metrics.network.1s"), sql).values
    assert db.flush_to_tier() == 120
    # flushed rows still answer identically from the mmap'd tier
    assert execute(db.table("flow_metrics.network.1s"),
                   sql).values == before
    db2 = Database(data_dir=d, storage=True)
    db2.load()
    t2 = db2.table("flow_metrics.network.1s")
    assert len(t2) == 120
    assert execute(t2, sql).values == before
    # string columns decode through the persisted dictionaries
    assert execute(t2, "SELECT host, Count() AS c FROM t GROUP BY host"
                   ).values == [["h1", 120.0]]


def test_flush_restart_torn_tail(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d, storage=True)
    _fill_net(db, n=60)
    db.flush_to_tier()
    _fill_net(db, n=60, t0=7000)
    db.flush_to_tier()
    segs = db.tier_store.tier("flow_metrics.network.1s").segments()
    assert len(segs) == 2
    # tear the SECOND commit's segment: restart must keep the first
    with open(segs[1].path, "r+b") as f:
        f.truncate(os.path.getsize(segs[1].path) - 8)
    db2 = Database(data_dir=d, storage=True)
    db2.load()
    t2 = db2.table("flow_metrics.network.1s")
    assert len(t2) == 60
    r = execute(t2, "SELECT Min(time) AS a, Max(time) AS b FROM t")
    assert r.values == [[6000.0, 6059.0]]


# -- eviction: ledger conservation + cache invalidation ---------------------

def test_ttl_eviction_ledger_conserved(tmp_path):
    db = Database(data_dir=str(tmp_path), storage=True)
    _fill_net(db, n=100, t0=6000)
    db.flush_to_tier()
    tele = Telemetry("server")
    jan = Janitor(db, ttl_s={"flow_metrics.network.1s": 100},
                  telemetry=tele)
    t = db.table("flow_metrics.network.1s")
    assert len(t) == 100
    # now - ttl is far past every row: the whole segment ages out
    assert jan.sweep_tier(now=1_000_000.0) == 100
    assert len(t) == 0
    assert db.tier_store.snapshot()["tables"][t.name]["segments"] == 0
    hop = tele.hop("storage").snapshot()
    assert hop["dropped"] == {"segment_evict": 100}
    assert hop["emitted"] == 100  # conserved: every drop was emitted
    assert jan.stats["tier_rows_evicted"] == 100
    assert jan.stats["tier_segments_evicted"] == 1


def test_size_budget_evicts_oldest_first(tmp_path):
    db = Database(data_dir=str(tmp_path), storage=True)
    _fill_net(db, n=50, t0=6000)
    db.flush_to_tier()
    _fill_net(db, n=50, t0=9000)
    db.flush_to_tier()
    snap = db.tier_store.snapshot()["tables"]["flow_metrics.network.1s"]
    assert snap["segments"] == 2
    jan = Janitor(db, ttl_s={}, tier_max_bytes=snap["bytes"] - 1)
    assert jan.sweep_tier(now=9100.0) == 50
    snap = db.tier_store.snapshot()["tables"]["flow_metrics.network.1s"]
    assert snap["segments"] == 1
    assert snap["tmin"] == 9000  # the older segment went first
    assert len(db.table("flow_metrics.network.1s")) == 50


def test_cache_invalidated_by_segment_evict(tmp_path):
    """Satellite regression: evicting a segment must invalidate cached
    results whose answers included its rows."""
    db = Database(data_dir=str(tmp_path), storage=True)
    t = _fill_net(db, n=100, t0=6000)
    db.flush_to_tier()
    cache = QueryCache()
    sql = "SELECT Sum(byte_tx) AS b FROM t"
    full = sum(range(100))
    assert cache.execute(t, sql).values == [[float(full)]]
    assert cache.execute(t, sql).values == [[float(full)]]
    assert cache.counters["hits"] == 1
    jan = Janitor(db, ttl_s={t.name: 100})
    assert jan.sweep_tier(now=1_000_000.0) == 100
    # the token moved: no stale hit, and the answer reflects the drop
    res = cache.execute(t, sql)
    assert cache.counters["hits"] == 1
    assert res.values in ([[None]], [[0.0]], [])


def test_flush_gen_moves_cache_token(tmp_path):
    from deepflow_tpu.query.cache import change_token
    db = Database(data_dir=str(tmp_path), storage=True)
    t = _fill_net(db, n=30)
    tok = change_token(t)
    db.flush_to_tier()  # same rows, different backing store
    assert change_token(t) != tok


# -- rollup datasources -----------------------------------------------------

def _horizons(db, now_s):
    job = RollupJob(db, lateness_s=0)
    job.roll(now_s=now_s)
    return job.horizons()


def test_rollup_selection_equals_raw():
    db = Database()
    raw = db.table("flow_metrics.network.1s")
    rows = []
    for minute in (100, 101, 102):
        for s in range(0, 60, 7):
            rows.append(dict(_NET_ROW, time=minute * 60 + s,
                             byte_tx=minute + s, packet_tx=2,
                             ip_src=f"10.0.0.{s % 2}"))
    raw.append_rows(rows)
    horizons = _horizons(db, now_s=103 * 60)
    sql = ("SELECT time(time, 60) AS m, ip_src, Sum(byte_tx) AS b, "
           "Sum(packet_tx) AS p FROM t "
           "WHERE time >= 6000 AND time < 6180 "
           "GROUP BY time(time, 60), ip_src ORDER BY m, ip_src")
    picked = qds.select_rollup(db, raw, S.parse(sql), horizons)
    assert picked is not None
    rtable, info = picked
    assert info["tier"] == "1m"
    assert rtable.name == "flow_metrics.network.1m"
    # byte-identical: the decomposable algebra re-aggregates exactly
    assert execute(rtable, sql).values == execute(raw, sql).values


def test_rollup_selection_rejections():
    db = Database()
    raw = db.table("flow_metrics.network.1s")
    raw.append_rows([dict(_NET_ROW, time=6000 + s, byte_tx=1)
                     for s in range(0, 120, 5)])
    horizons = _horizons(db, now_s=6180)

    def sel(sql):
        return qds.select_rollup(db, raw, S.parse(sql), horizons)

    # eligible baseline
    assert sel("SELECT Sum(byte_tx) AS b FROM t "
               "WHERE time >= 6000 AND time < 6120") is not None
    # no upper time bound: the window never closes under any horizon
    assert sel("SELECT Sum(byte_tx) AS b FROM t "
               "WHERE time >= 6000") is None
    # mid-bucket bound would slice rolled buckets
    assert sel("SELECT Sum(byte_tx) AS b FROM t "
               "WHERE time >= 6000 AND time < 6090") is None
    # upper bound past the completeness horizon: late rows missing
    assert sel("SELECT Sum(byte_tx) AS b FROM t "
               "WHERE time >= 6000 AND time < 9999960") is None
    # Count() is not a rollup aggregator (rows collapse)
    assert sel("SELECT Count() AS c FROM t "
               "WHERE time >= 6000 AND time < 6120") is None
    # org scoping: org_id is NOT a rollup tag, so scoped queries
    # auto-reject (rolled rows collapse across orgs)
    assert sel("SELECT Sum(byte_tx) AS b FROM t WHERE org_id = 3 "
               "AND time >= 6000 AND time < 6120") is None
    # row-level query: raw timestamps must survive
    assert sel("SELECT time, byte_tx FROM t "
               "WHERE time >= 6000 AND time < 6120") is None
    # Avg's denominator is the ROW count, which rolling collapses
    assert sel("SELECT Avg(byte_tx) AS a FROM t "
               "WHERE time >= 6000 AND time < 6120") is None
    # the decomposable ratio spelling stays selectable
    assert sel("SELECT Sum(rtt_sum) / Sum(rtt_count) AS r FROM t "
               "WHERE time >= 6000 AND time < 6120") is not None


def test_rollup_1h_equals_raw_recompute():
    db = Database()
    raw = db.table("flow_metrics.network.1s")
    rows = []
    for h in (10, 11):
        for m in range(0, 60, 13):
            rows.append(dict(_NET_ROW, time=h * 3600 + m * 60,
                             byte_tx=h * m + 1))
    raw.append_rows(rows)
    horizons = _horizons(db, now_s=13 * 3600)
    sql = ("SELECT time(time, 3600) AS h, Sum(byte_tx) AS b FROM t "
           "WHERE time >= 36000 AND time < 43200 "
           "GROUP BY time(time, 3600) ORDER BY h")
    picked = qds.select_rollup(db, raw, S.parse(sql), horizons)
    assert picked is not None and picked[1]["tier"] == "1h"
    assert execute(picked[0], sql).values == execute(raw, sql).values


def test_sketch_percentile_within_gamma():
    db = Database()
    raw = db.table("flow_metrics.application.1s")
    rng = np.random.default_rng(7)
    vals = rng.integers(100, 1_000_000, size=300)
    raw.append_rows([
        {"time": 6000 + i // 3, "ip_src": "1.1.1.1", "ip_dst": "2.2.2.2",
         "server_port": 443, "l7_protocol": 1, "app_service": "shop",
         "request": 1, "rrt_sum": int(v), "rrt_count": 1,
         "rrt_max": int(v)} for i, v in enumerate(vals)])
    horizons = _horizons(db, now_s=6180)
    sql = ("SELECT PERCENTILE(rrt_max, 95) AS p FROM t "
           "WHERE time >= 6000 AND time < 6120")
    got = qds.sketch_percentile(db, raw, S.parse(sql), horizons)
    assert got is not None
    res, info = got
    assert info["approx"] == "ddsketch" and info["tier"] == "1m"
    assert res.columns == ["p"]
    exact = execute(raw, sql).values[0][0]
    # DDSketch gamma=1.02 relative-error bound (plus rank-interp slack)
    assert abs(res.values[0][0] - exact) / exact < 0.05
    # grouped variant keys correctly
    sql_g = ("SELECT app_service, PERCENTILE(rrt_max, 50) AS p FROM t "
             "WHERE time >= 6000 AND time < 6120 GROUP BY app_service")
    got = qds.sketch_percentile(db, raw, S.parse(sql_g), horizons)
    assert got is not None
    assert got[0].values[0][0] == "shop"


def test_rollup_sketch_merges_upward():
    """1m sketches merge into the 1h tier; the merged state answers the
    same percentile the 1m states do (merge is exact on the sketch)."""
    db = Database()
    raw = db.table("flow_metrics.application.1s")
    raw.append_rows([
        {"time": 36000 + i * 60, "ip_src": "1.1.1.1", "ip_dst": "2.2.2.2",
         "server_port": 443, "l7_protocol": 1, "app_service": "s",
         "request": 1, "rrt_max": 1000 * (i + 1)} for i in range(60)])
    job = RollupJob(db, lateness_s=0)
    job.roll(now_s=14 * 3600)
    h1 = db.table("flow_metrics.application.1h")
    states = [v for v in
              execute(h1, "SELECT rrt_max_sketch FROM t").values
              if v[0]]
    assert states, "1h tier carries merged sketch state"
    from deepflow_tpu.cluster.sketch import HistogramSketch
    sk = HistogramSketch.from_dict(json.loads(states[0][0]))
    assert sk.count == 60


# -- durability gate --------------------------------------------------------

def test_gate_release_only_after_commit(tmp_path):
    db = Database(data_dir=str(tmp_path), storage=True)
    _fill_net(db, n=10)
    gate = DurabilityGate()
    tracker = SeqAckTracker()
    tracker.seed(7, -1)
    for seq in range(3):
        gate.add(7, seq)
    fl = Flusher(db, gate=gate, seq_tracker=tracker)
    assert tracker.contiguous(7) == -1  # parked, not acked
    assert fl.flush_once() == 10
    assert tracker.contiguous(7) == 2  # released after the commit
    assert len(gate) == 0
    # the same rename persisted the floors: a SIGKILL now re-acks
    ts = TieredStore(os.path.join(str(tmp_path), "segments"))
    ts.recover()
    assert ts.ack_floors == {7: 2}


def test_group_commit_seals_only_for_pending_acks(tmp_path):
    """The flusher's group-commit fast path: a cycle with no acks
    waiting must not chop the open stripe buffers into per-interval
    sliver chunks — the rows stay in RAM until a chunk seals naturally
    or durability is actually owed."""
    db = Database(data_dir=str(tmp_path), storage=True)
    t = _fill_net(db, n=50)
    fl = Flusher(db, gate=DurabilityGate())
    assert fl.flush_once() == 0  # empty gate: nothing owed, no seal
    assert len(t) == 50          # rows still served from RAM
    snap = db.tier_store.snapshot()["tables"]
    assert snap.get("flow_metrics.network.1s", {}).get("rows", 0) == 0
    fl.gate.add(9, 0)            # now an ack waits on durability
    assert fl.flush_once() == 50
    snap = db.tier_store.snapshot()["tables"]
    assert snap["flow_metrics.network.1s"]["rows"] == 50
    assert len(t) == 50


def test_gate_requeues_on_commit_failure(tmp_path, monkeypatch):
    db = Database(data_dir=str(tmp_path), storage=True)
    _fill_net(db, n=5)
    gate = DurabilityGate()
    tracker = SeqAckTracker()
    tracker.seed(3, -1)
    gate.add(3, 0)
    fl = Flusher(db, gate=gate, seq_tracker=tracker)
    monkeypatch.setattr(db, "flush_to_tier",
                        lambda ack_floors=None, seal=True,
                        compress=True: 1 / 0)
    with pytest.raises(ZeroDivisionError):
        fl.flush_once()
    assert len(gate) == 1  # stays gated: the rows are not durable
    assert tracker.contiguous(3) == -1


# -- multi-lane receiver ----------------------------------------------------

def test_receiver_lane_fanout():
    from deepflow_tpu.codec import MessageType

    recv = Receiver(port=0, enable_udp=False)
    qs = recv.register(MessageType.L4_LOG, lanes=3)
    assert isinstance(qs, list) and len(qs) == 3
    # connection lanes round-robin; one connection -> one queue
    assert recv._lane_q(qs, 0) is qs[0]
    assert recv._lane_q(qs, 1) is qs[1]
    assert recv._lane_q(qs, 4) is qs[1]
    # single-lane registration keeps the scalar contract
    q = recv.register(MessageType.PROFILE, lanes=1)
    assert not isinstance(q, list)
    assert recv._lane_q(q, 9) is q


def test_receiver_lane_dispatch_preserves_order():
    from deepflow_tpu.codec import FrameHeader, MessageType

    recv = Receiver(port=0, enable_udp=False)
    qs = recv.register(MessageType.L4_LOG, lanes=2)

    def hdr(agent, seq):
        return FrameHeader(MessageType.L4_LOG, agent_id=agent, seq=seq)

    # two connections, one per agent, pinned to different lanes
    recv._dispatch_many([(hdr(1, s), b"a%d" % s) for s in range(4)],
                        lane=0)
    recv._dispatch_many([(hdr(2, s), b"b%d" % s) for s in range(4)],
                        lane=1)
    _, group0 = qs[0].get_nowait()
    _, group1 = qs[1].get_nowait()
    assert [h.seq for h, _ in group0] == [0, 1, 2, 3]
    assert all(h.agent_id == 1 for h, _ in group0)
    assert [h.seq for h, _ in group1] == [0, 1, 2, 3]
    assert all(h.agent_id == 2 for h, _ in group1)
    assert qs[0].empty() and qs[1].empty()


# -- spool age retention ----------------------------------------------------

def test_spool_age_eviction(tmp_path):
    from deepflow_tpu.agent.spool import Spool

    evicted = []
    sp = Spool(str(tmp_path), segment_bytes=4096, max_age_s=100,
               on_evict=lambda n, reason: evicted.append((n, reason)))
    payload = b"x" * 2000
    for seq in range(1, 7):  # rotates across several segments
        sp.append(1, seq, payload)
    assert len(sp._segments) > 2
    # age the closed segments far past the cutoff
    for seg in sp._segments[:-1]:
        seg.mtime -= 10_000
    sp.append(1, 7, payload)
    assert evicted and all(r == "spool_age_evict" for _, r in evicted)
    # the open writer survives regardless of age
    assert sp.pending_records() >= 1
    assert sp.max_seq() == 7
    sp.close()
